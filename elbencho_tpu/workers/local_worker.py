"""LocalWorker: one I/O worker thread — the workload engine's heart.

Reference: source/workers/LocalWorker.{h,cpp} (8.5 kLoC) — per-phase re-init
of function pointers + offset generator (initPhaseFunctionPointers
:1210-1379), the giant phase dispatch in run() (:193-418), dir-mode
iteration with the deterministic namespace ``r<rank>/d<dir>/r<rank>-f<file>``
(:3097), file/bdev striping (:3511-3769), the sync hot loop rwBlockSized
(:1702-1814), integrity verify (:2124-2212), block variance refill (:2242),
rwmix per-op split (:1741), sync/dropcaches (:8075/:8118).

The TPU data path replaces the reference's CUDA staging (allocGPUIOBuffer
:1427-1537, cudaMemcpy wrappers :2437-2490, cuFile wrappers :2633-2749):
workers map to TPU chips by ``rank % len(tpu_ids)`` (as the reference does
for GPUs, :1444) and stage blocks into HBM via PjRt transfers — see
elbencho_tpu/tpu/device.py. The function seam (func_positional_read/write +
tpu pre/post hooks) is kept so the C++ ioengine and the TPU path plug into
the same spots.
"""

from __future__ import annotations

import errno
import mmap
import os
import stat as stat_mod
import time

import numpy as np

from ..phases import BenchMode, BenchPathType, BenchPhase, phase_name
from ..toolkits import logger
from ..toolkits.file_tk import FileRangeLock
from ..toolkits.offset_gen import (OffsetGenRandom, OffsetGenRandomAligned,
                                   OffsetGenRandomAlignedFullCoverage,
                                   OffsetGenReverseSeq, OffsetGenSequential,
                                   OffsetGenStrided)
from ..toolkits.random_algos import create_rand_algo
from ..toolkits.rate_limiter import RateLimiter
from .base import Worker
from .shared import WorkerException, WorkerInterruptedException

MKFILE_MODE = 0o644  # reference: MKFILE_MODE, Common.h:96
MKDIR_MODE = 0o755

class LocalWorker(Worker):
    def __init__(self, shared, rank: int):
        super().__init__(shared, rank)
        self.cfg = shared.config
        # io_depth staging slots so async/pipelined paths never overwrite
        # a block still in flight (reference: allocIOBuffer x iodepth,
        # :1386). All slots come from the unified staging pool
        # (utils/staging_pool.py) — one allocator owns the hugepage/NUMA/
        # registration lifecycle for every data path.
        self._staging_pool = None
        self._io_bufs: "list[memoryview]" = []
        self._io_buf: "memoryview | None" = None
        self._own_path_fds: "list[int]" = []
        self._path_fds: "list[int]" = []
        self._rand_offset_algo = None
        self._block_var_algo = None
        self._rate_limiter_read: "RateLimiter | None" = None
        self._rate_limiter_write: "RateLimiter | None" = None
        self._tpu = None           # TpuWorkerContext when --tpuids given
        self._numa_zone = None     # set when --zones bound this worker
        # --tpuslice: per-chip ingest bytes of a context-less mesh feeder
        # (statistics reads this when _tpu is None, the RemoteWorker idiom)
        self.tpu_per_chip: "dict[int, tuple[int, int]]" = {}
        self._ops_log = None
        self._num_iops_submitted = 0  # rwmix modulo counter
        self._prepared = False
        self._stream_mode_logged = False  # once-per-phase fused-loop note
        self._stream_drain_failed = False  # aborted ring drain: leak bufs
        self._io_retrier = None        # --ioretries (workers/io_errors.py)
        self._tolerate_note_logged = False  # partial-dataset delete note
        # --slowops: the entry path the CURRENT block loop works on, so a
        # captured tail op can name its file (dir mode sets it per file;
        # file/bdev mode falls back to the first bench path)
        self._slowop_path = ""
        import ctypes
        self._native_interrupt = ctypes.c_int(0)  # seen by the C++ engine

    def interrupt_execution(self) -> None:
        super().interrupt_execution()
        self._native_interrupt.value = 1

    def reset_stats(self) -> None:
        super().reset_stats()
        self._native_interrupt.value = 0
        self.tpu_per_chip = {}
        self._stream_mode_logged = False  # log the mode once per phase
        self._tolerate_note_logged = False
        self._slowop_path = ""  # re-resolved by the phase's entry loop
        if self._io_retrier is not None:
            self._io_retrier.reset()  # per-phase backoff budget
        if self._tpu is not None:
            # path-audit counters are per-phase, like tpu_transfer_bytes
            self._tpu.reset_path_counters()
        if self._staging_pool is not None:
            # pool audit counters are per-phase; the POOL persists
            self._staging_pool.reset_counters()

    # ------------------------------------------------------------------
    # preparation (reference: preparePhase, LocalWorker.cpp:424)
    # ------------------------------------------------------------------

    def prepare(self) -> None:
        cfg = self.cfg
        self._apply_core_binding()
        if cfg.file_size > 0 or cfg.tree_file_path \
                or cfg.bench_mode in (BenchMode.NETBENCH, BenchMode.S3):
            self._alloc_io_buffer()
        self._s3_client = None  # created lazily by workers/s3_worker.py
        if cfg.tpu_multihost and (cfg.tpu_ids or cfg.run_tpu_slice):
            # join the pod-wide runtime BEFORE first device use so jax
            # meshes span every host (idempotent + lock-safe across
            # concurrently-prepping worker threads and re-preps)
            from ..parallel.mesh import init_multihost
            init_multihost(cfg.tpu_multihost)
        if cfg.tpu_ids:
            from ..tpu.device import TpuWorkerContext
            chip = cfg.tpu_ids[self.rank % len(cfg.tpu_ids)]
            # --tpudepth overrides the iodepth ride-along (the
            # reference's cuFile iodepth analogue). Under --tpudirect the
            # depth is clamped to the host-buffer count: an unbatched
            # direct import aliases its host buffer until the ring drains
            # it, and buffer rotation only guarantees that when the ring
            # is no deeper than the rotation period.
            depth = max(cfg.tpu_depth or cfg.io_depth, 1)
            if cfg.use_tpu_direct and depth > max(cfg.io_depth, 1):
                if self.rank % max(1, cfg.num_threads) == 0:
                    logger.log(
                        logger.LOG_NORMAL,
                        f"NOTE: --tpudepth {depth} exceeds --iodepth "
                        f"{cfg.io_depth}; clamped to {max(cfg.io_depth, 1)} "
                        f"under --tpudirect (a host buffer must not be "
                        f"rewritten before its zero-copy import drained)")
                depth = max(cfg.io_depth, 1)
            self._tpu = TpuWorkerContext(
                chip_id=chip, block_size=cfg.block_size,
                direct=cfg.use_tpu_direct, verify_on_device=cfg.do_tpu_verify,
                pipeline_depth=depth,
                hbm_limit_pct=cfg.tpu_hbm_limit_pct,
                batch_blocks=max(cfg.tpu_batch_blocks, 1),
                dispatch_budget_usec=cfg.tpu_dispatch_budget_usec,
                staging_pool=self._staging_pool)
            if self._tracer is not None:
                # dispatch-vs-DMA sub-spans ride the transfer pipeline
                self._tpu.set_tracer(self._tracer, self.rank)
            needs_fill = (cfg.run_create_files
                          or (cfg.run_tpu_bench
                              and cfg.tpu_bench_pattern in ("d2h", "both")))
            if needs_fill and not cfg.integrity_check_salt:
                self._tpu.warmup_fill()  # jit outside the timed phase
            needs_ingest = (cfg.run_read_files
                            or (cfg.run_tpu_bench
                                and cfg.tpu_bench_pattern in ("h2d",
                                                              "both")))
            if needs_ingest and not cfg.use_tpu_direct:
                # copy-step jit + donation probe outside the timed phase
                # (and outside the --tpubudget accounting). Skipped in
                # direct mode: its primary path never stages, and the
                # warmup would pin pipeline_depth full-size HBM staging
                # blocks in _slot_prev for the whole run — headroom
                # --tpuhbmpct exists to protect. (The direct->staged
                # fallback then jit-compiles lazily; that run is already
                # off its fast path and says so loudly.)
                self._tpu.warmup_transfer()
        if cfg.bench_path_type != BenchPathType.DIR \
                and cfg.bench_mode == BenchMode.POSIX:
            self._prepare_path_fds()
        if cfg.ops_log_path:
            from ..toolkits.ops_logger import OpsLogger
            self._ops_log = OpsLogger(cfg.ops_log_path, self.rank,
                                      use_lock=cfg.ops_log_lock)
        if cfg.bench_mode == BenchMode.NETBENCH:
            from .netbench import prepare_netbench
            prepare_netbench(self)  # cross-host connect/accept barrier
        self._rand_offset_algo = create_rand_algo(
            cfg.rand_offset_algo, seed=None)
        if cfg.block_variance_pct:
            self._block_var_algo = create_rand_algo(cfg.block_variance_algo)
        if cfg.limit_read_bps:
            self._rate_limiter_read = RateLimiter(cfg.limit_read_bps)
        if cfg.limit_write_bps:
            self._rate_limiter_write = RateLimiter(cfg.limit_write_bps)
        # --ioretries: per-op transient-error retry (None = exact
        # fail-fast parity; workers/io_errors.py)
        from .io_errors import make_io_retrier
        self._io_retrier = make_io_retrier(self)
        # native limiter windows (RateState x2: read, write); created once
        # per prepare and shared by this worker's phases — the exact
        # lifetime of the Python RateLimiter objects above
        import ctypes
        self._native_rl_state = (ctypes.c_uint64 * 4)()
        # load (and first time: build) the native engine here, OUTSIDE the
        # timed phase, so `make` never charges to a measured result
        from ..utils.native import get_native_engine
        native = get_native_engine()
        if cfg.io_engine != "auto":
            # explicitly requested engines must never silently fall back
            if native is None:
                raise WorkerException(
                    f"--ioengine {cfg.io_engine} requires the native "
                    f"ioengine (csrc/libioengine.so failed to build/load)")
            if cfg.io_engine == "uring" and not native.uring_supported():
                raise WorkerException(
                    "--ioengine uring: this kernel does not support "
                    "io_uring (compiled out or disabled via sysctl)")
        self._prepared = True

    def cleanup(self) -> None:
        for fd in self._own_path_fds:
            try:
                os.close(fd)
            except OSError:
                pass
        self._own_path_fds = []
        if getattr(self, "_s3_pipeline", None) is not None:
            self._s3_pipeline.close()
            self._s3_pipeline = None
        if self._tpu is not None:
            self._tpu.close()  # drop device arrays before buffer teardown
            self._tpu = None
        self._io_buf = None
        if self._staging_pool is not None:
            # ONE teardown for every staging buffer (io slots + TPU
            # aggregation aux slabs). A failed stream-ring drain leaks
            # the slab to process teardown inside the pool — kernel DMA
            # may still target it (the old gc.collect()-guarded mmap
            # dance and the module leak list both lived here).
            if getattr(self, "_stream_drain_failed", False):
                self._staging_pool.leak()
            self._staging_pool.close()
            self._staging_pool = None
        self._io_bufs = []
        if self._ops_log is not None:
            self._ops_log.close()
        if getattr(self, "_s3_client", None) is not None:
            # --s3single: the client is the process-wide singleton other
            # workers may still be using — only a per-worker client is
            # closed here (the singleton's sockets close on GC/rebuild)
            if not getattr(self.cfg, "use_s3_client_singleton", False):
                self._s3_client.close()
            self._s3_client = None
        if getattr(self, "_netbench_conns", None):
            from .netbench import cleanup_netbench
            cleanup_netbench(self)

    def _apply_core_binding(self) -> None:
        """Round-robin worker->core binding (reference: --cores/--zones via
        NumaTk; here sched_setaffinity, NUMA zones via utils/numa)."""
        cfg = self.cfg
        if cfg.cpu_cores_str:
            from ..toolkits.units import parse_uint_list
            cores = parse_uint_list(cfg.cpu_cores_str)
            if cores:
                core = cores[self.rank % len(cores)]
                try:
                    os.sched_setaffinity(0, {core})
                except OSError as err:
                    logger.log_error(f"core binding failed: {err}")
        elif cfg.numa_zones_str:
            from ..utils.numa import bind_to_numa_zone
            from ..toolkits.units import parse_uint_list
            zones = parse_uint_list(cfg.numa_zones_str)
            if zones:
                zone = zones[self.rank % len(zones)]
                # binds CPU affinity AND thread memory policy; the zone
                # is kept so _alloc_io_buffer can mbind the buffers too
                if bind_to_numa_zone(zone):
                    self._numa_zone = zone

    def _alloc_io_buffer(self) -> None:
        """One unified staging pool per worker, one slot per iodepth
        (replaces the reference's posix_memalign x iodepth,
        LocalWorker.cpp:1386-1401, AND this worker's former bespoke
        per-slot mmaps): hugepage-backed where available, O_DIRECT-
        aligned, NUMA-bound to the worker's --zones zone, and — where
        the kernel provides io_uring — registered ONCE as fixed buffers
        shared by the classic block loop and the streaming ring
        (--iosqpoll rides on the same ring). Slots are pre-filled with
        random data so writes aren't trivially compressible."""
        from ..utils.staging_pool import StagingPool
        cfg = self.cfg
        self._staging_pool = StagingPool(
            max(cfg.io_depth, 1), max(cfg.block_size, 1),
            numa_zone=self._numa_zone,
            fill_algo=create_rand_algo("fast", seed=self.rank + 1),
            madvise_flags=cfg.madvise_flags,
            register=cfg.pool_registration != "off",
            want_sqpoll=cfg.io_sqpoll,
            sqpoll_idle_ms=cfg.io_sqpoll_idle_ms,
            log_rank=self.rank)
        self._io_bufs = self._staging_pool.views
        self._io_buf = self._io_bufs[0]

    def _prepare_path_fds(self) -> None:
        """File/blockdev mode FDs. Shared FDs live in cfg.bench_path_fds
        (opened once by the WorkerManager); --nofdsharing makes each worker
        open its own (reference: prepareBenchPathFDsVec, ProgArgs.cpp:1981)."""
        cfg = self.cfg
        if cfg.bench_path_fds and not cfg.no_fd_sharing:
            self._path_fds = cfg.bench_path_fds
            return
        flags = os.O_RDWR
        if cfg.run_create_files or cfg.scenario_creates_files:
            flags |= os.O_CREAT
        if cfg.use_direct_io:
            flags |= os.O_DIRECT
        self._own_path_fds = [os.open(p, flags, MKFILE_MODE)
                              for p in cfg.paths]
        self._path_fds = self._own_path_fds

    # ------------------------------------------------------------------
    # phase loop (reference: LocalWorker::run, LocalWorker.cpp:193-418)
    # ------------------------------------------------------------------

    def run(self) -> None:
        self.prepare()
        # capture the current uuid BEFORE signalling prep-done: the
        # coordinator may start the first phase the moment the last worker
        # checks in, and we must notice that uuid change
        last_uuid = self.shared.bench_uuid
        self.shared.inc_num_workers_done()  # prep barrier
        try:
            while True:
                phase, last_uuid = self.shared.wait_for_phase_change(last_uuid)
                if phase == BenchPhase.TERMINATE:
                    return
                if phase == BenchPhase.IDLE:
                    continue
                self.reset_stats()
                try:
                    while True:
                        self._dispatch_phase(phase)
                        if not self.cfg.do_infinite_io_loop:
                            break
                        self.check_interruption_request(force=True)
                    self.finish_phase_stats()
                    self.shared.inc_num_workers_done()
                except WorkerInterruptedException:
                    self.finish_phase_stats()
                    self.shared.inc_num_workers_done()
                except Exception as err:  # noqa: BLE001
                    logger.log_error(
                        f"Worker {self.rank} phase "
                        f"{phase.name} failed: {type(err).__name__}: {err}")
                    self.shared.inc_num_workers_done_with_error(err)
        finally:
            self.cleanup()

    def _dispatch_phase(self, phase: BenchPhase) -> None:
        cfg = self.cfg
        self._num_iops_submitted = 0
        self._loader_pacer = self._make_loader_pacer(
            is_write=(phase != BenchPhase.READFILES))
        # --rwmixthr: the first N local ranks of a WRITE phase run the READ
        # workload instead, accounted as rwmix-read (reference: rwmix-threads
        # reader conversion, LocalWorker.cpp:1054-1062)
        if (phase == BenchPhase.CREATEFILES
                and cfg.num_rwmix_read_threads
                and (self.rank % max(1, cfg.num_threads))
                < cfg.num_rwmix_read_threads):
            self._run_as_rwmix_reader()
            return
        self._dispatch_phase_inner(phase)

    def _run_as_rwmix_reader(self) -> None:
        """Swap accounting to the rwmix-read counters, run the read
        workload, swap back."""
        def swap():
            self.live_ops, self.live_ops_rwmix_read = \
                self.live_ops_rwmix_read, self.live_ops
            self.iops_latency_histo, self.iops_latency_histo_rwmix = \
                self.iops_latency_histo_rwmix, self.iops_latency_histo
            self.entries_latency_histo, self.entries_latency_histo_rwmix = \
                self.entries_latency_histo_rwmix, self.entries_latency_histo

        swap()
        self._rwmix_thread_reader = True
        try:
            self._dispatch_phase_inner(BenchPhase.READFILES)
        finally:
            self._rwmix_thread_reader = False
            swap()

    def _dispatch_phase_inner(self, phase: BenchPhase) -> None:
        cfg = self.cfg
        if phase == BenchPhase.SYNC:
            self._any_mode_sync()
        elif phase == BenchPhase.DROPCACHES:
            self._any_mode_drop_caches()
        elif phase == BenchPhase.TPUBENCH:
            from .tpubench import run_tpubench_phase
            run_tpubench_phase(self, phase)
        elif phase == BenchPhase.TPUSLICE:
            from .tpuslice import run_tpu_slice_phase
            run_tpu_slice_phase(self, phase)
        elif cfg.bench_mode == BenchMode.S3:
            from .s3_worker import dispatch_s3_phase
            dispatch_s3_phase(self, phase)
        elif cfg.bench_mode == BenchMode.HDFS:
            from .hdfs_worker import dispatch_hdfs_phase
            dispatch_hdfs_phase(self, phase)
        elif cfg.bench_mode == BenchMode.NETBENCH:
            from .netbench import run_netbench_phase
            run_netbench_phase(self, phase)
        elif phase in (BenchPhase.CREATEDIRS, BenchPhase.DELETEDIRS,
                       BenchPhase.STATDIRS):
            self._dir_mode_iterate_dirs(phase)
        elif cfg.bench_path_type == BenchPathType.DIR:
            if cfg.tree_file_path:
                self._custom_tree_iterate_files(phase)
            else:
                self._dir_mode_iterate_files(phase)
        else:
            self._file_mode_phase(phase)

    # ------------------------------------------------------------------
    # dir mode (reference: dirModeIterateDirs :2811 / IterateFiles :3055)
    # ------------------------------------------------------------------

    @staticmethod
    def dir_rel_path_for(rank: int, dir_idx: int, dir_sharing: bool) -> str:
        """Namespace: "r<rank>/d<idx>", or shared "d<idx>" with --dirsharing
        (reference: LocalWorker.cpp:3097 + dirsharing)."""
        if dir_sharing:
            return f"d{dir_idx}"
        return f"r{rank}/d{dir_idx}"

    @staticmethod
    def file_rel_path_for(rank: int, dir_idx: int, file_idx: int,
                          dir_sharing: bool) -> str:
        base = LocalWorker.dir_rel_path_for(rank, dir_idx, dir_sharing)
        return f"{base}/r{rank}-f{file_idx}"

    def _dir_rel_path(self, dir_idx: int) -> str:
        return self.dir_rel_path_for(self.rank, dir_idx,
                                     self.cfg.do_dir_sharing)

    def _file_rel_path(self, dir_idx: int, file_idx: int) -> str:
        return self.file_rel_path_for(self.rank, dir_idx, file_idx,
                                      self.cfg.do_dir_sharing)

    def _bench_path_for_dir(self, dir_idx: int) -> str:
        """Round-robin dirs over bench paths (reference: :3110)."""
        paths = self.cfg.paths
        return paths[(self.rank + dir_idx) % len(paths)]

    def _dir_mode_iterate_dirs(self, phase: BenchPhase) -> None:
        cfg = self.cfg
        if cfg.do_dir_sharing and self.rank % cfg.num_threads != 0 \
                and phase != BenchPhase.STATDIRS:
            # with dirsharing only one local worker creates/deletes the
            # shared dirs (others would collide)
            self.got_phase_work = False
            return
        for dir_idx in range(cfg.num_dirs):
            self.check_interruption_request(force=True)
            base = self._bench_path_for_dir(dir_idx)
            rel = self._dir_rel_path(dir_idx)
            path = os.path.join(base, rel)
            with self.oplog(phase.name.lower(), path) as op_rec:
                t0 = time.perf_counter_ns()
                if phase == BenchPhase.CREATEDIRS:
                    os.makedirs(path, MKDIR_MODE, exist_ok=True)
                elif phase == BenchPhase.DELETEDIRS:
                    try:
                        os.rmdir(path)
                        parent = os.path.dirname(path)
                        if os.path.basename(parent).startswith("r"):
                            try:
                                os.rmdir(parent)  # remove empty rank dir
                            except OSError:
                                pass
                    except FileNotFoundError:
                        if not cfg.ignore_delete_errors \
                                and not self._partial_tolerance(phase):
                            raise
                        op_rec.error = True
                else:  # STATDIRS
                    os.stat(path)
                lat_usec = (time.perf_counter_ns() - t0) // 1000
            self.entries_latency_histo.add_latency(lat_usec)
            self.live_ops.num_entries_done += 1

    _NATIVE_FILE_OPS = {BenchPhase.CREATEFILES: "write",
                        BenchPhase.READFILES: "read",
                        BenchPhase.STATFILES: "stat",
                        BenchPhase.DELETEFILES: "unlink"}

    def _can_use_native_file_loop(self, native, phase: BenchPhase) -> bool:
        """The whole open->blocks->close per-file loop runs in C++ when no
        per-op Python feature is active (the LOSF hot path; reference:
        dirModeIterateFiles is native there by construction)."""
        cfg = self.cfg
        return (self._native_loop_eligible(native)
                and self._ops_log is None  # per-entry records stay Python
                and phase in self._NATIVE_FILE_OPS
                and cfg.io_engine in ("auto", "sync")
                and cfg.io_depth <= 1
                and not cfg.do_stat_inline
                and not cfg.do_prealloc_file
                and not cfg.do_truncate_to_size
                and not cfg.fadvise_flags
                and not cfg.use_mmap
                and not cfg.use_random_offsets
                and not cfg.do_reverse_seq_offsets
                # the native per-file loop generates its own sequential
                # offsets; the shuffle-window permutation feeds the
                # gen-driven loops instead
                and not cfg.shuffle_window)

    def _run_native_file_loop(self, native, phase: BenchPhase) -> None:
        """Chunked delegation of the per-file loop to the C++ engine."""
        cfg = self.cfg
        op = self._NATIVE_FILE_OPS[phase]
        if phase == BenchPhase.CREATEFILES:
            open_flags = self._open_flags_write()
        else:
            open_flags = os.O_RDONLY | (os.O_DIRECT if cfg.use_direct_io
                                        else 0)
        if op in ("write", "read") and cfg.file_size:
            # cap each native call at ~8192 blocks AND ~256 MiB of I/O so
            # live stats/stonewall snapshots stay fresh (same bounds as
            # _native_chunk_blocks)
            blocks_per_file = max(
                (cfg.file_size + cfg.block_size - 1) // cfg.block_size, 1)
            chunk = max(1, min(
                self._NATIVE_CHUNK_MAX_BLOCKS // blocks_per_file,
                self._NATIVE_CHUNK_MAX_BYTES // cfg.file_size))
        else:
            # stat/unlink: no block I/O, only path batching
            chunk = self._NATIVE_CHUNK_MAX_BLOCKS
        paths: "list[str]" = []
        from ..utils.native import NativeVerifyError

        def submit():
            self.check_interruption_request(force=True)

            def call(paths=paths):
                native.run_file_loop(
                    paths, op, open_flags, cfg.file_size, cfg.block_size,
                    # stat/unlink (and 0-byte files) never touch the buffer
                    buf_addr=self._buf_addr() if self._io_bufs else 0,
                    ignore_delete_errors=cfg.ignore_delete_errors
                    or self._partial_tolerance(phase),
                    worker=self, interrupt_flag=self._native_interrupt,
                    verify_salt=cfg.integrity_check_salt,
                    block_var_pct=cfg.block_variance_pct,
                    block_var_seed=self._block_var_seed(),
                    rwmix_pct=cfg.rwmix_read_pct
                    if phase == BenchPhase.CREATEFILES else 0,
                    limit_read_bps=cfg.limit_read_bps,
                    limit_write_bps=cfg.limit_write_bps,
                    rl_state=self._native_rl_state,
                    inline_readback=(cfg.do_read_inline
                                     or cfg.do_direct_verify),
                    flock_mode=self._flock_mode_code())

            try:
                # unlink chunks never retry: a re-run would ENOENT on the
                # files the first attempt already removed
                self._retrying_native(call, retryable=op != "unlink")
            except NativeVerifyError as err:
                bpf = max((cfg.file_size + cfg.block_size - 1)
                          // cfg.block_size, 1)
                file_off = (err.block_idx % bpf) * cfg.block_size \
                    + err.word_idx * 8
                raise WorkerException(
                    f"data integrity check failed at file offset "
                    f"{file_off} of {paths[err.block_idx // bpf]}: "
                    f"expected {err.want:#x}, got {err.got:#x}"
                    + self._verify_fail_hint(err.got)) from None
            except FileNotFoundError as err:
                if phase == BenchPhase.CREATEFILES \
                        and not cfg.run_create_dirs:
                    # parity hint (reference: dirModeOpenAndPrepFile :7395)
                    raise WorkerException(
                        "File create/open failed. Did you forget to enable "
                        "directory creation ('--mkdirs'/-d)?") from err
                raise

        for dir_idx in range(cfg.num_dirs):
            base = self._bench_path_for_dir(dir_idx)
            for file_idx in range(cfg.num_files):
                paths.append(os.path.join(
                    base, self._file_rel_path(dir_idx, file_idx)))
                if len(paths) >= chunk:
                    submit()
                    paths = []
        if paths:
            submit()

    def _dir_mode_iterate_files(self, phase: BenchPhase) -> None:
        """open -> [stat-inline] -> block loop -> close per file; entry
        latency histogram per file (reference: dirModeIterateFiles
        :3055-3281, unlinkat/fstatat for del/stat :3237-3249)."""
        cfg = self.cfg
        from ..utils.native import get_native_engine
        native = get_native_engine()
        if self._can_use_native_file_loop(native, phase):
            self._run_native_file_loop(native, phase)
            return
        for dir_idx in range(cfg.num_dirs):
            for file_idx in range(cfg.num_files):
                self.check_interruption_request(force=True)
                base = self._bench_path_for_dir(dir_idx)
                path = os.path.join(base,
                                    self._file_rel_path(dir_idx, file_idx))
                with self.oplog(phase.name.lower(), path) as op_rec:
                    t0 = time.perf_counter_ns()
                    if phase == BenchPhase.CREATEFILES:
                        self._write_one_file(path)
                    elif phase == BenchPhase.READFILES:
                        self._read_one_file(path)
                    elif phase == BenchPhase.STATFILES:
                        os.stat(path)
                    elif phase == BenchPhase.DELETEFILES:
                        try:
                            os.unlink(path)
                        except FileNotFoundError:
                            if not cfg.ignore_delete_errors \
                                    and not self._partial_tolerance(phase):
                                raise
                            op_rec.error = True
                    lat_usec = (time.perf_counter_ns() - t0) // 1000
                self.entries_latency_histo.add_latency(lat_usec)
                self.live_ops.num_entries_done += 1
                if self._tracer is not None:
                    self._tracer.record_op(
                        phase.name.lower(), phase_name(phase), t0,
                        lat_usec, self.rank, 0, cfg.file_size)
                if self._slowops is not None and phase in (
                        BenchPhase.STATFILES, BenchPhase.DELETEFILES):
                    # entry-granular phases: the whole entry IS the op
                    # (create/read capture per-block records inside
                    # _rw_block_sized instead)
                    self._slowops.record(
                        phase.name.lower(), phase_name(phase), lat_usec,
                        0, cfg.file_size, path=path, start_ns=t0)

    def _open_flags_write(self) -> int:
        cfg = self.cfg
        flags = os.O_WRONLY | os.O_CREAT
        if cfg.rwmix_read_pct or cfg.do_read_inline or cfg.do_direct_verify:
            flags = os.O_RDWR | os.O_CREAT
        if cfg.use_direct_io:
            flags |= os.O_DIRECT
        if cfg.do_truncate:
            flags |= os.O_TRUNC
        return flags

    def _write_one_file(self, path: str) -> None:
        cfg = self.cfg
        self._slowop_path = path  # --slowops: name the file in captures
        try:
            flags = self._open_flags_write()
            if cfg.use_mmap:
                # a writable mapping needs a read-write fd
                flags = (flags & ~os.O_WRONLY) | os.O_RDWR
            fd = os.open(path, flags, MKFILE_MODE)
        except FileNotFoundError as err:
            if not cfg.run_create_dirs:
                # parity hint (reference: dirModeOpenAndPrepFile :7395)
                raise WorkerException(
                    f"File create/open failed. Did you forget to enable "
                    f"directory creation ('--mkdirs'/-d)? Path: {path}"
                ) from err
            raise
        try:
            if cfg.do_stat_inline:
                os.fstat(fd)
            if cfg.do_prealloc_file and cfg.file_size:
                os.posix_fallocate(fd, 0, cfg.file_size)
            if cfg.do_truncate_to_size:
                os.ftruncate(fd, cfg.file_size)
            if cfg.file_size:
                if cfg.use_mmap:
                    self._rw_block_sized_mmap(fd, is_write=True)
                else:
                    gen = self._make_offset_gen_for_file(is_write=True)
                    self._rw_block_sized(fd, gen, is_write=True)
            self._apply_fadvise(fd)
        finally:
            os.close(fd)

    def _read_one_file(self, path: str) -> None:
        cfg = self.cfg
        self._slowop_path = path  # --slowops: name the file in captures
        flags = os.O_RDONLY
        if cfg.use_direct_io:
            flags |= os.O_DIRECT
        fd = os.open(path, flags)
        try:
            self._apply_fadvise(fd)
            if cfg.do_stat_inline:
                os.fstat(fd)  # --statinline (reference: stat-inline :3140)
            if cfg.file_size:
                if cfg.use_mmap:
                    self._rw_block_sized_mmap(fd, is_write=False)
                else:
                    gen = self._make_offset_gen_for_file(is_write=False)
                    self._rw_block_sized(fd, gen, is_write=False)
        finally:
            os.close(fd)

    def _apply_fadvise(self, fd: int) -> None:
        flags_str = self.cfg.fadvise_flags
        if not flags_str:
            return
        advice_map = {"seq": os.POSIX_FADV_SEQUENTIAL,
                      "rand": os.POSIX_FADV_RANDOM,
                      "willneed": os.POSIX_FADV_WILLNEED,
                      "dontneed": os.POSIX_FADV_DONTNEED,
                      "noreuse": os.POSIX_FADV_NOREUSE}
        for name in flags_str.split(","):
            name = name.strip()
            if not name:
                continue
            if name not in advice_map:
                raise WorkerException(f"unknown fadvise flag: {name}")
            os.posix_fadvise(fd, 0, 0, advice_map[name])

    # ------------------------------------------------------------------
    # offset generator wiring (reference: initPhaseRWOffsetGen :1141-1186)
    # ------------------------------------------------------------------

    def _make_shuffle_gen(self, num_bytes: int, start: int = 0):
        """--shufflewindow: seeded windowed permutation (every block
        exactly once, locality bounded by the window) — the
        training-pipeline shuffle-buffer shape. ONE seed mix for both
        the dir-mode and shared-file constructions: the scenario epoch
        (so the epochs scenario re-shuffles per epoch) times a prime,
        plus the worker rank (so workers don't read in lockstep)."""
        cfg = self.cfg
        bs = cfg.block_size
        from ..toolkits.offset_gen import OffsetGenShuffleWindow
        return OffsetGenShuffleWindow(
            num_bytes, bs, max(cfg.shuffle_window, bs),
            seed=cfg.scenario_epoch * 1_000_003 + self.rank, start=start)

    def _make_offset_gen_for_file(self, is_write: bool):
        cfg = self.cfg
        size, bs = cfg.file_size, cfg.block_size
        if not is_write and cfg.shuffle_window:
            return self._make_shuffle_gen(size)
        if cfg.use_random_offsets:
            amount = max(cfg.random_amount // max(1, cfg.num_dataset_threads),
                         bs) if cfg.random_amount else size
            if cfg.no_random_align:
                return OffsetGenRandom(self._rand_offset_algo, amount, bs,
                                       range_len=size)
            if is_write:
                # full-coverage LCG: every block exactly once (default for
                # aligned random writes, reference LocalWorker.cpp:1177-1184)
                return OffsetGenRandomAlignedFullCoverage(
                    self._rand_offset_algo, amount, bs, range_len=size)
            return OffsetGenRandomAligned(self._rand_offset_algo, amount, bs,
                                          range_len=size)
        if cfg.do_reverse_seq_offsets:
            return OffsetGenReverseSeq(size, bs)
        return OffsetGenSequential(size, bs)

    # ------------------------------------------------------------------
    # hot loop (reference: rwBlockSized, LocalWorker.cpp:1702-1814)
    # ------------------------------------------------------------------

    def _rw_block_sized(self, fd: int, gen, is_write: bool,
                        file_offset_base: int = 0,
                        multi_file: "object | None" = None,
                        stripe: "tuple | None" = None) -> None:
        """offset-gen loop -> rate limit -> [rwmix decision] -> [fill buf] ->
        positional I/O -> [verify] -> [TPU H2D] -> latency + counters.

        When the native C++ ioengine is available and the workload qualifies
        (no TPU staging — see ``_native_loop_eligible``), the whole loop is
        delegated to it: verify, rwmix-pct, block variance, rate limits,
        flock, inline read-back and opslog records all run INSIDE the engine
        (BlockMod), and striped multi-file mode maps through
        ``stripe=(fds, file_size)`` (the structured form of the
        ``multi_file`` mapping).
        """
        cfg = self.cfg
        if stripe is not None and multi_file is None:
            # single source of truth: derive the Python-fallback mapping
            # from the structured stripe info
            stripe_fds, stripe_size = stripe

            def multi_file(global_off, length):  # noqa: ARG001
                return (stripe_fds[global_off // stripe_size],
                        global_off % stripe_size)
        from ..utils.native import get_native_engine
        native = get_native_engine()
        # fused TPU streaming ring (--tpustream): storage I/O runs in the
        # engine's submission/completion ring while Python overlaps HBM
        # DMA dispatch — the default on eligible --tpuids phases, with a
        # clean fallback chain native-stream (uring -> AIO) -> Python
        # loop, logged once per phase
        if self._tpu is not None and cfg.tpu_stream != "off":
            blocker = self._tpu_stream_blocker(native, multi_file, stripe,
                                               gen)
            if blocker is None:
                if self._run_fused_tpu_stream_loop(
                        native, fd, gen, is_write, file_offset_base,
                        stripe):
                    return
                blocker = ("stream ring setup failed, or the pinned "
                           "--ioengine is not the ring's actual backend")
            if cfg.tpu_stream == "on":
                raise WorkerException(
                    f"--tpustream on: fused native-stream loop "
                    f"unavailable ({blocker})")
            self._log_stream_mode(
                f"NOTE: fused TPU stream ineligible ({blocker}); "
                f"using the Python loop")
        sync_path = cfg.io_depth <= 1 and cfg.io_engine in ("auto", "sync")
        if (self._native_loop_eligible(native)
                and (multi_file is None or stripe is not None)
                # per-op flock and inline read-back are sync-loop features
                # (in C++ too); async engines fall back to Python for them
                and (sync_path or not (cfg.do_read_inline
                                       or cfg.do_direct_verify
                                       or cfg.use_file_locks))):
            if self._run_native_block_loop(native, fd, gen, is_write,
                                           file_offset_base, stripe):
                return
        if cfg.io_engine != "auto":
            raise WorkerException(
                f"--ioengine {cfg.io_engine} only supports the native "
                f"block loop — incompatible with --rwmixthrpct/--tpuids/"
                f"--tracefile/non-'fast' --blockvaralgo (and "
                f"--verifydirect/--readinline/--flock need the sync "
                f"engine)")
        num_bufs = len(self._io_bufs)
        # the pacer is PER PHASE (created in _dispatch_phase): dir-mode
        # read phases enter here once per file, and the consume clock /
        # batch count must span the whole epoch, not restart per shard
        pacer = None if is_write else getattr(self, "_loader_pacer", None)
        is_rwmix_reader = getattr(self, "_rwmix_thread_reader", False)
        # the byte-ratio balancer only applies to the mixed WRITE phase
        # (writers + converted readers); a later pure READ phase must not
        # be throttled against zero writer bytes
        balancer = (self.shared.rwmix_balancer
                    if (is_write or is_rwmix_reader) else None)
        # chaos-test seams: a deterministic per-op delay for exactly one
        # (port, op_index), and a uniform every-op latency floor (the
        # autotune suite's constructed storage bottleneck) — both None/0
        # outside ELBENCHO_TPU_TESTING fleets
        from ..telemetry.slowops import test_op_delay, test_uniform_op_delay
        fault_delay = test_op_delay(cfg)
        uniform_delay_usec = test_uniform_op_delay(cfg)
        for off, length in gen:
            # rotate buffers so pipelined TPU transfers never race a reuse
            buf = self._io_bufs[self._num_iops_submitted % num_bufs]
            do_read_this_op = (not is_write) or self._rwmix_decides_read()
            limiter = (self._rate_limiter_read if do_read_this_op
                       else self._rate_limiter_write)
            if limiter or balancer:
                # limiter/balancer sleeps can be long; check every op here
                self.check_interruption_request(force=True)
                if balancer:
                    if do_read_this_op or is_rwmix_reader:
                        balancer.wait_read(length)
                    else:
                        balancer.wait_write(length)
                if limiter:
                    limiter.wait(length)
            else:
                self.check_interruption_request()
            if multi_file is not None:
                fd, real_off = multi_file(off, length)
            else:
                real_off = file_offset_base + off
            # --slowops stage split: bracket this op's TPU hand-offs
            # (D2H pre-write fill here, H2D post-read below) with the
            # context's dispatch/DMA accounting so a captured tail op
            # says WHERE its time went
            tpu_snap = ((self._tpu.dispatch_usec, self._tpu.transfer_usec)
                        if self._slowops is not None
                        and self._tpu is not None else None)
            slow_r0 = self.io_retries if self._slowops is not None else 0
            if not do_read_this_op:
                self._pre_write_fill(buf, real_off, length)

            def one_op(fd=fd, real_off=real_off, length=length,
                       do_read=do_read_this_op, buf=buf,
                       delay=uniform_delay_usec + (
                           fault_delay[1]
                           if fault_delay is not None
                           and self._num_iops_submitted
                           == fault_delay[0] else 0)):
                """One positional I/O attempt; a short transfer raises
                the (transient) ShortIOError so --ioretries covers it."""
                t0 = time.perf_counter_ns()
                if delay:  # chaos-test seam: provably slow op
                    time.sleep(delay / 1e6)
                if cfg.use_file_locks:
                    with FileRangeLock(fd, cfg.use_file_locks, real_off,
                                       length, is_write=not do_read):
                        if do_read:
                            n = os.preadv(fd, [buf[:length]], real_off)
                        else:
                            n = os.pwritev(fd, [buf[:length]], real_off)
                elif do_read:
                    n = os.preadv(fd, [buf[:length]], real_off)
                else:
                    n = os.pwritev(fd, [buf[:length]], real_off)
                if n != length:
                    from .io_errors import ShortIOError
                    raise ShortIOError(do_read, real_off, n, length)
                # t0 rides along for the tracer span (the final
                # successful attempt's window, excluding retry backoff)
                return n, (time.perf_counter_ns() - t0) // 1000, t0

            try:
                if self._io_retrier is None:
                    n, lat_usec, t0 = one_op()
                else:
                    n, lat_usec, t0 = self._io_retrier.run(
                        one_op, path=self._retry_path_hint())
            except OSError as err:
                from .io_errors import ShortIOError
                if isinstance(err, ShortIOError):
                    # exact historic short-I/O message (fail-fast parity)
                    raise WorkerException(str(err)) from None
                raise
            if self._ops_log:
                self._ops_log.log_op("read" if do_read_this_op else "write",
                                     "", real_off, length)
            if do_read_this_op:
                self._post_read_actions(buf, real_off, length)
            elif cfg.do_read_inline or cfg.do_direct_verify:
                self._inline_read_back(fd, buf, real_off, length)
            ops = (self.live_ops_rwmix_read
                   if (is_write and do_read_this_op) else self.live_ops)
            histo = (self.iops_latency_histo_rwmix
                     if (is_write and do_read_this_op)
                     else self.iops_latency_histo)
            histo.add_latency(lat_usec)
            if self._tracer is not None:  # no-op path: one attribute test
                self._tracer.record_op(
                    "read" if do_read_this_op else "write",
                    phase_name(self.shared.current_phase), t0, lat_usec,
                    self.rank, real_off, length,
                    slot=self._num_iops_submitted % num_bufs)
            if self._slowops is not None:  # no-op path: one attribute test
                self._slowops.record(
                    "read" if do_read_this_op else "write",
                    phase_name(self.shared.current_phase), lat_usec,
                    real_off, length,
                    path=self._slowop_path
                    or (cfg.paths[0] if cfg.paths else ""),
                    retries=self.io_retries - slow_r0,
                    dispatch_usec=(self._tpu.dispatch_usec - tpu_snap[0]
                                   if tpu_snap is not None else 0),
                    dma_usec=(self._tpu.transfer_usec - tpu_snap[1]
                              if tpu_snap is not None else 0),
                    slot=self._num_iops_submitted % num_bufs,
                    start_ns=t0)
            ops.num_bytes_done += n
            ops.num_iops_done += 1
            self._num_iops_submitted += 1
            if self._staging_pool is not None:
                self._staging_pool.account_ops(1)
            if pacer is not None:
                # dataloader emulation: decode burn + consume-cadence
                # wait per closed batch (--scenario dataloader)
                pacer.on_block()
        if self._tpu is not None:
            # drain pipelined transfers before phase end (guarded: an
            # in-flight transfer of a dying chip surfaces here)
            self._tpu_guarded(self._tpu.flush)
            self._sync_tpu_usec()

    def _sync_tpu_usec(self) -> None:
        """Mirror the context's split timing counters into this worker's
        phase stats (dispatch = host-side submit cost, transfer = DMA
        wall time; both accumulated per-phase by TransferPipeline)."""
        self.tpu_dispatch_usec = self._tpu.dispatch_usec
        self.tpu_transfer_usec = self._tpu.transfer_usec

    # ------------------------------------------------------------------
    # data-plane fault tolerance (--ioretries / --iotimeout /
    # --tpufallback; workers/io_errors.py + tpu/device.py failover)
    # ------------------------------------------------------------------

    def _partial_tolerance(self, phase: BenchPhase) -> bool:
        """Delete phases tolerate missing entries when an earlier write
        phase of this run was aborted (time limit, interrupt, or a
        permanent storage error): the dataset is partial by definition,
        and failing the cleanup over expected ENOENTs would bury the
        benchmark results that were already printed. Logged once per
        phase; --nodelerr keeps covering the cross-run cleanup case."""
        if phase not in (BenchPhase.DELETEFILES, BenchPhase.DELETEDIRS):
            return False
        if not self.shared.partial_dataset:
            return False
        if not self._tolerate_note_logged:
            self._tolerate_note_logged = True
            if self.rank % max(1, self.cfg.num_threads) == 0:
                logger.log(
                    logger.LOG_NORMAL,
                    "NOTE: an earlier write phase was aborted; the delete "
                    "phase tolerates entries missing from the partial "
                    "dataset")
        return True

    def _retry_path_hint(self) -> str:
        """Path used by the retry classifier's network-filesystem check
        (EIO is transient on NFS/FUSE/parallel filesystems, permanent on
        local media)."""
        paths = self.cfg.paths
        return paths[0] if paths else ""

    def _retrying_native(self, call, retryable: bool = True):
        """Run one native-engine chunk call under --ioretries. A retry
        re-issues the WHOLE chunk (accounting only books after a chunk
        succeeds, so nothing double-counts; re-running completed
        read/write ops is idempotent benchmark I/O)."""
        if self._io_retrier is None or not retryable:
            return call()
        return self._io_retrier.run(call, path=self._retry_path_hint())

    def _tpu_guarded(self, fn, *args, **kwargs):
        """Run one TPU transfer-path call with device-loss failover
        (--tpufallback). Anything that is not a classified XLA-runtime/
        device-loss error propagates untouched — a --tpubudget breach or
        a logic error must abort, never failover."""
        from ..tpu.device import is_device_loss_error
        attempts = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except WorkerInterruptedException:
                raise
            except Exception as err:  # noqa: BLE001 - classified below
                if self._tpu is None or not is_device_loss_error(err):
                    raise
                attempts += 1
                if attempts > len(self.cfg.tpu_ids) + 1:
                    raise  # every chip (and host staging) failed: abort
                self._tpu_handle_device_loss(err)

    def _tpu_handle_device_loss(self, err: Exception) -> None:
        """One device-loss event: poison the chip fleet-wide, then abort
        (default), fail over to a surviving --tpuids chip, or degrade to
        host-memory staging per --tpufallback."""
        cfg = self.cfg
        ctx = self._tpu
        with self.shared.cond:
            self.shared.poisoned_tpu_chips.add(ctx.chip_id)
        if self._tracer is not None:  # --tracefile failover marker
            self._tracer.record("tpu_failover", "fault",
                                self._tracer.now_ns(), 0, rank=self.rank)
        mode = getattr(cfg, "tpu_fallback", "abort")
        if mode == "abort":
            from ..tpu.device import TpuDeviceLostError
            raise WorkerException(
                str(TpuDeviceLostError(ctx.chip_id, err))) from err
        if mode == "chip":
            with self.shared.cond:
                survivors = [c for c in cfg.tpu_ids
                             if c not in self.shared.poisoned_tpu_chips]
            if survivors:
                ctx.failover_to_chip(survivors[self.rank % len(survivors)])
                return
            logger.log_error(
                "--tpufallback chip: no surviving --tpuids chip left; "
                "degrading to host-memory staging")
        ctx.failover_to_host()

    def _loader_pacing_active(self, is_write: bool) -> bool:
        """Dataloader-emulation pacing (--scenario dataloader) shapes the
        READ loop with per-batch decode burns and consume-cadence waits —
        per-op Python behavior no native loop expresses."""
        cfg = self.cfg
        return (not is_write
                and bool(cfg.scenario_step_usec or cfg.scenario_decode_usec))

    def _make_loader_pacer(self, is_write: bool):
        if not self._loader_pacing_active(is_write):
            return None
        from ..toolkits.rate_limiter import DataLoaderPacer
        cfg = self.cfg
        return DataLoaderPacer(
            cfg.scenario_batch_blocks or 1, cfg.scenario_step_usec,
            cfg.scenario_decode_usec, cfg.scenario_prefetch or 1,
            interrupt_check=lambda:
                self.check_interruption_request(force=True))

    def _native_loop_eligible(self, native) -> bool:
        """Conditions every native delegation shares: no per-op Python
        feature may be active. Verify/rwmix-pct/block-variance run INSIDE
        the native loop (csrc BlockMod — the reference keeps them in its
        hot loop too, LocalWorker.cpp:1741,2124,2242) and so do the
        per-thread rate limiters (C++ RateLimiter.h analogue); what still
        drops to Python is TPU staging, the rwmix-threads byte-ratio
        balancer, and non-default variance PRNGs (opslog block records
        are written by the engine; dir-mode entry records stay Python). Loop-specific
        extras (flock, read-inline...) are checked at the call sites."""
        cfg = self.cfg
        return (native is not None
                and self._tpu is None
                # --tracefile spans are recorded by the Python loops (the
                # fused TPU stream loop records its own and stays native)
                and self._tracer is None
                # --slowops captures per-op context (path/offset/retry
                # chain) the block-loop arrays don't carry — same
                # fallback rule as tracing; the fused stream ring stays
                # engaged and records from its reap events
                and self._slowops is None
                and self.shared.rwmix_balancer is None
                # dataloader-emulation pacing is per-op Python behavior
                # (the knobs are only set on the loader read leg, so a
                # scenario's setup write still runs native)
                and not (cfg.scenario_step_usec or cfg.scenario_decode_usec)
                and (not cfg.block_variance_pct
                     or cfg.block_variance_algo == "fast"))

    # ------------------------------------------------------------------
    # fused TPU streaming ring (--tpustream): the engine keeps up to
    # iodepth storage ops in flight over the registered staging slots
    # (GIL released across the blocking reap), Python reaps completed
    # slots and hands them straight to the TPU transfer pipeline — disk
    # DMA in the kernel overlaps HBM DMA dispatch in Python, the
    # cuFileRead overlap of the reference's GPUDirect path
    # (LocalWorker.cpp:2633-2749) rebuilt on io_uring/AIO + PjRt.
    # ------------------------------------------------------------------

    @staticmethod
    def _stripe_offsets(offsets, file_offset_base, stripe_size):
        """Vectorized calcFileIdxAndOffsetStriped (LocalWorker.cpp:2084):
        global block offsets -> (per-block fd index or None, in-file
        offsets). The ONE mapping shared by the native block loop and
        the fused stream loop, so the two paths can never diverge on
        which file region a block lands in."""
        if stripe_size:
            goffs = offsets + np.uint64(file_offset_base)
            return ((goffs // np.uint64(stripe_size)).astype(np.uint32),
                    goffs % np.uint64(stripe_size))
        if file_offset_base:
            return None, offsets + np.uint64(file_offset_base)
        return None, offsets

    def _log_stream_mode(self, msg: str) -> None:
        """Once per phase, from the first local worker only."""
        if self._stream_mode_logged:
            return
        self._stream_mode_logged = True
        if self.rank % max(1, self.cfg.num_threads) == 0:
            logger.log(logger.LOG_NORMAL, msg)

    def _tpu_stream_blocker(self, native, multi_file, stripe,
                            gen=None) -> "str | None":
        """Why the fused native-stream loop cannot serve this phase
        (None = eligible). Everything the stream cannot express stays on
        the Python loop: per-op Python features, and explicit engine
        pins that don't match the kernel's stream backend."""
        from ..utils.native import ENGINE_CODES
        cfg = self.cfg
        if native is None:
            return "native ioengine unavailable"
        if cfg.bench_path_type == BenchPathType.DIR and gen is not None:
            # dir-mode/custom-tree phases open one stream PER FILE: for
            # files only a couple of ring-fills long, the ring setup +
            # registration + teardown would outweigh the overlap it buys
            ops = getattr(gen, "num_bytes", 0) // max(cfg.block_size, 1)
            if ops < 2 * max(len(self._io_bufs), 1):
                return "per-file stream too short to amortize ring setup"
        if not native.stream_supported():
            return "kernel lacks both io_uring and AIO"
        if multi_file is not None and stripe is None:
            return "unstructured multi-file mapping"
        if self._ops_log is not None:
            return "--opslog per-op records"
        if self.shared.rwmix_balancer is not None:
            return "--rwmixthr byte-ratio balancer"
        if cfg.use_file_locks:
            return "--flock per-op locks"
        if cfg.do_read_inline or cfg.do_direct_verify:
            return "--readinline/--verifydirect inline read-back"
        if self._rate_limiter_read or self._rate_limiter_write:
            return "per-op rate limits"
        if cfg.scenario_step_usec or cfg.scenario_decode_usec:
            return "dataloader-emulation pacing (--scenario dataloader)"
        if cfg.io_engine != "auto" and \
                ENGINE_CODES.get(cfg.io_engine) != native.stream_backend():
            return (f"--ioengine {cfg.io_engine} pinned but the stream "
                    f"backend is {native.stream_backend_name()}")
        return None

    def _run_fused_tpu_stream_loop(self, native, fd, gen, is_write,
                                   file_offset_base,
                                   stripe=None) -> bool:
        """Drive the whole block loop through the engine's streaming
        ring. Returns False when the ring cannot be opened (the caller
        logs the fallback and runs the Python loop). Accounting goes
        through the array-based _account_chunk per drained chunk, with
        the dispatch-vs-DMA split riding the TransferPipeline counters
        exactly like the Python loop."""
        from collections import deque
        from ..utils.native import NativeStreamError
        cfg = self.cfg
        if stripe is not None:
            fds, stripe_size = list(stripe[0]), stripe[1]
        else:
            fds, stripe_size = [fd], 0
        pool = self._staging_pool
        slot_addrs = pool.slot_addrs
        try:
            # borrow the pool's persistent ring where one exists: the
            # slab was registered as fixed buffers ONCE at pool open
            # (and SQPOLL rides along) — else an owned per-phase ring
            stream = native.open_stream(
                fds, slot_addrs, max(cfg.block_size, 1),
                pool=None if pool.broken else pool.native_pool)
        except NativeStreamError:
            return False
        if cfg.io_engine != "auto":
            # the open may have fallen back (e.g. uring probe ok but
            # ring mmaps ENOMEM at this slot count): an explicit
            # --ioengine pin is enforced against the ACTUAL backend
            from ..utils.native import ENGINE_CODES
            if ENGINE_CODES.get(cfg.io_engine) != stream.backend:
                stream.close()
                return False
        self._log_stream_mode(
            f"fused TPU stream engaged (backend={stream.backend_name}, "
            f"slots={len(slot_addrs)}"
            + (", pool-registered" if stream.pooled else "")
            + (", sqpoll" if stream.sqpoll else "") + ")")
        if cfg.io_timeout_secs:
            # --iotimeout: hung ops surface as -ETIMEDOUT with the slot
            # re-armed instead of wedging the reap loop
            stream.set_timeout(cfg.io_timeout_secs * 1_000_000)
        fault_spec = os.environ.get("ELBENCHO_TPU_IO_FAULT")
        if fault_spec:
            # test-only deterministic fault injection; config validation
            # already rejected this knob outside a test harness
            stream.set_fault_from_spec(fault_spec)
        if self._tracer is not None:  # stream-reap sub-spans (--tracefile)
            stream.tracer = self._tracer
            stream.trace_rank = self.rank
        # slot-reuse discipline: a slot is free, in the engine ring
        # (slot_op), or held back after its H2D until the transfer ring
        # provably drained its zero-copy import (holdback_depth). The
        # depth is FROZEN for the phase: if the direct path latches off
        # mid-stream, dropping it live would release slots whose
        # earlier zero-copy imports are still in the ring undrained —
        # holding staged-era slots a little longer is merely
        # conservative, the reverse is a use-after-reuse.
        hold = self._tpu.holdback_depth()
        free = deque(range(len(slot_addrs)))
        held: "deque[int]" = deque()
        slot_op: dict = {}
        chunk = self._native_chunk_blocks()
        try:
            while True:
                batch = gen.next_batch(chunk)
                if batch is None:
                    break
                self._fused_stream_chunk(stream, batch, is_write,
                                         file_offset_base, stripe_size,
                                         free, held, slot_op, hold)
        finally:
            # drains outstanding kernel DMA first; a failed drain means
            # the kernel still owns ops targeting the slot buffers —
            # cleanup() must then leak the mmaps to process teardown
            # instead of unmapping memory a late completion DMAs into
            if stream.close() != 0:
                self._stream_drain_failed = True
                logger.log_error(
                    f"worker {self.rank}: stream ring drain failed; "
                    f"keeping I/O buffers mapped until process exit")
        # phase-end transfer drain + --tpubudget check (guarded for
        # --tpufallback like every other transfer-path call)
        self._tpu_guarded(self._tpu.flush)
        self._sync_tpu_usec()
        return True

    def _fused_stream_chunk(self, stream, batch, is_write,
                            file_offset_base, stripe_size, free, held,
                            slot_op, hold) -> None:
        """One bounded chunk of the fused loop: submit every op (reaping
        for slots as needed), then drain to a chunk barrier so the
        array-based accounting is exact; an interrupt books the
        completed-prefix estimate before propagating (the same contract
        as the interrupted native block loop)."""
        import ctypes
        from ..utils.native import _account_chunk
        cfg = self.cfg
        ctx = self._tpu
        offsets, lengths = batch
        n = len(offsets)
        if n == 0:
            return
        fd_idx, real_offs = self._stripe_offsets(offsets,
                                                 file_offset_base,
                                                 stripe_size)
        flags = self._rwmix_read_flags(n) if is_write else None
        lengths_np = (lengths if isinstance(lengths, np.ndarray)
                      else np.asarray(lengths, dtype=np.uint64))
        total = int(lengths_np.sum())
        lat_arr = (ctypes.c_uint64 * n)()
        state = {"bytes": 0}

        def retry_or_raise(slot, i, fdi, r_off, length, rd, attempts,
                           err) -> bool:
            """--ioretries for a failed fused-ring op: backoff, then
            re-submit the SAME op on the SAME slot (the slot buffer still
            holds the write source; a read retries into it). attempts is
            tracked PER OP in slot_op — the ring interleaves many
            in-flight ops, so the retrier's shared consecutive counter
            would misaccount across them. Returns True when the retry
            was submitted, raises the original error when retries are
            off/exhausted/not applicable."""
            from .io_errors import IoRetryBudgetExhausted, ShortIOError
            retrier = self._io_retrier
            if retrier is None or not retrier.should_retry(
                    err, path=self._retry_path_hint(), attempt=attempts):
                if isinstance(err, ShortIOError):
                    raise WorkerException(str(err)) from None
                raise err
            try:
                retrier.backoff(attempt=attempts)
            except IoRetryBudgetExhausted:
                raise err from None
            slot_op[slot] = (i, fdi, r_off, length, rd, attempts + 1)
            stream.submit(slot, fdi, r_off, length, is_write=not rd)
            return True

        def reap_some(min_complete: int) -> None:
            from .io_errors import ShortIOError
            events = stream.reap(min_complete, 1000,
                                 self._native_interrupt)
            if self._staging_pool is not None:
                # registration/SQPOLL audit (PoolRegisteredOps and co)
                self._staging_pool.account_stream_events(stream,
                                                         len(events))
            if not events:
                # timeout or interrupt: surface the interrupt, else retry
                self.check_interruption_request(force=True)
                if cfg.io_timeout_secs and slot_op:
                    # un-cancellable hung op (kernel-AIO io_cancel is
                    # best-effort): once an op is WAY past the deadline
                    # with no completion in sight, abort the phase
                    # loudly instead of spinning forever — the ring's
                    # close() drain then leaks the slot buffers safely
                    age = stream.oldest_age_usec()
                    limit = cfg.io_timeout_secs * 2_000_000 + 5_000_000
                    if age > limit:
                        raise WorkerException(
                            f"storage op stuck for {age // 1_000_000}s — "
                            f"past --iotimeout {cfg.io_timeout_secs}s and "
                            f"uncancellable on the "
                            f"{stream.backend_name} backend; aborting "
                            f"the phase")
                return
            for slot, lat, res in events:
                i, fdi, r_off, length, rd, attempts = slot_op.pop(slot)
                if res < 0:
                    if -res == errno.ETIMEDOUT:
                        # --iotimeout cancelled a hung op (audited; the
                        # error itself is transient, so --ioretries can
                        # re-drive the op on the re-armed slot)
                        self.io_timeouts += 1
                    retry_or_raise(slot, i, fdi, r_off, length, rd,
                                   attempts,
                                   OSError(-res, os.strerror(-res)))
                    continue
                if res != length:
                    retry_or_raise(slot, i, fdi, r_off, length, rd,
                                   attempts,
                                   ShortIOError(rd, r_off, res, length))
                    continue
                lat_arr[i] = lat
                state["bytes"] += res
                ctx.stream_fused_ops += 1
                if self._tracer is not None:
                    # span start back-derived from the engine's latency
                    self._tracer.record_op(
                        "read" if rd else "write",
                        phase_name(self.shared.current_phase),
                        self._tracer.now_ns() - int(lat) * 1000, lat,
                        self.rank, r_off, length, slot=slot)
                if self._slowops is not None:
                    # per-op latency straight from the engine's reap
                    # event; file attribution via the stripe fd index
                    self._slowops.record(
                        "read" if rd else "write",
                        phase_name(self.shared.current_phase), int(lat),
                        r_off, length,
                        path=(self._slowop_path
                              or (cfg.paths[fdi]
                                  if fdi < len(cfg.paths) else "")),
                        retries=attempts,
                        slot=slot,
                        start_ns=(time.perf_counter_ns()
                                  - int(lat) * 1000))
                if rd:
                    # host->HBM DMA + verify (host memcmp or on-device),
                    # identical to the Python loop's post-read hook
                    self._post_read_actions(self._io_bufs[slot], r_off,
                                            length)
                    if hold:  # frozen per phase, see the caller
                        held.append(slot)
                        while len(held) > hold:
                            free.append(held.popleft())
                    else:
                        free.append(slot)
                else:
                    free.append(slot)

        try:
            for i in range(n):
                self.check_interruption_request()
                while not free:
                    if slot_op:
                        reap_some(0)  # harvest anything already done
                        if free:
                            break
                    if held:
                        # release the oldest ingested slot by draining
                        # its H2D from the transfer ring: after
                        # drain_to(len(held)-1) the ring's FIFO in-flight
                        # window only covers the newer held slots, so
                        # held[0]'s import has provably completed.
                        # Without this, the holdback would cap the engine
                        # ring at n_slots-(depth-1) ops and serialize
                        # storage I/O under --tpudirect.
                        ctx.drain_to(len(held) - 1)
                        free.append(held.popleft())
                    else:
                        reap_some(1)
                slot = free.popleft()
                length = int(lengths_np[i])
                r_off = int(real_offs[i])
                rd = bool(flags[i]) if (is_write and flags is not None) \
                    else not is_write
                if not rd:
                    # write-source block originates in HBM: D2H into the
                    # slot (the Python loop's pre-write hook)
                    self._pre_write_fill(self._io_bufs[slot], r_off,
                                         length)
                fdi = int(fd_idx[i]) if fd_idx is not None else 0
                slot_op[slot] = (i, fdi, r_off, length, rd, 0)
                stream.submit(slot, fdi, r_off, length, is_write=not rd)
                if self._staging_pool is not None:
                    self._staging_pool.note_occupancy(len(slot_op))
            while slot_op:  # chunk barrier: exact accounting below
                reap_some(1)
        except WorkerInterruptedException:
            _account_chunk(self, lat_arr, lengths_np, n, state["bytes"],
                           total, flags)
            raise
        _account_chunk(self, lat_arr, lengths_np, n, state["bytes"],
                       total, flags)

    #: bounds for one native engine call, so live stats progress and
    #: interrupts stay responsive (shared by every native delegation)
    _NATIVE_CHUNK_MAX_BLOCKS = 8192
    _NATIVE_CHUNK_MAX_BYTES = 256 << 20

    def _native_chunk_blocks(self) -> int:
        cfg = self.cfg
        max_bytes = self._NATIVE_CHUNK_MAX_BYTES
        # under a rate limit, one engine call must not span minutes of
        # throttled I/O (live stats only refresh between chunks): cap a
        # chunk at ~2 seconds of the tightest active budget
        limits = [x for x in (cfg.limit_read_bps, cfg.limit_write_bps) if x]
        if limits:
            max_bytes = min(max_bytes, 2 * min(limits))
        by_bytes = max_bytes // max(cfg.block_size, 1)
        return max(1, min(self._NATIVE_CHUNK_MAX_BLOCKS, by_bytes))

    def _run_native_block_loop(self, native, fd, gen, is_write,
                               file_offset_base, stripe=None) -> bool:
        """Delegate the block loop to the C++ engine in chunks (bounded
        memory, live-stats progress, interruptibility between chunks);
        counters and latency buckets sync back per chunk. The engine also
        polls our interrupt flag every 128 ops within a chunk. With
        ``stripe=(fds, file_size)`` global offsets map to per-block
        (file, in-file offset) pairs (calcFileIdxAndOffsetStriped).
        Verify/rwmix-pct/variance run inside the engine (BlockMod)."""
        from ..utils.native import NativeVerifyError
        cfg = self.cfg
        chunk = self._native_chunk_blocks()
        stripe_fds, stripe_size = stripe if stripe else (None, 0)

        def submit(offsets, lengths):
            self.check_interruption_request(force=True)
            idx, offsets = self._stripe_offsets(offsets, file_offset_base,
                                                stripe_size)
            fds = stripe_fds if stripe_fds else None
            # per-op modulo split, vectorized (reference:
            # (workerRank+numIOPSSubmitted)%100 < pct, :1741-1742)
            flags = self._rwmix_read_flags(len(offsets)) if is_write \
                else None

            def call(offsets=offsets, lengths=lengths, idx=idx, fds=fds,
                     flags=flags):
                native.run_block_loop(
                    fd=fd, offsets=offsets, lengths=lengths,
                    is_write=is_write, buf_addr=self._buf_addr(),
                    iodepth=cfg.io_depth, worker=self,
                    interrupt_flag=self._native_interrupt,
                    engine=cfg.io_engine, fds=fds, fd_idx=idx,
                    op_is_read=flags,
                    verify_salt=cfg.integrity_check_salt,
                    block_var_pct=cfg.block_variance_pct,
                    block_var_seed=self._block_var_seed(),
                    limit_read_bps=cfg.limit_read_bps,
                    limit_write_bps=cfg.limit_write_bps,
                    rl_state=self._native_rl_state,
                    inline_readback=(cfg.do_read_inline
                                     or cfg.do_direct_verify),
                    flock_mode=self._flock_mode_code(),
                    ops_fd=(self._ops_log.fd if self._ops_log is not None
                            else -1),
                    ops_lock=cfg.ops_log_lock, worker_rank=self.rank,
                    # classic-engine leg of the unified pool: the uring
                    # engine runs this chunk over the pool's persistent
                    # ring + once-registered fixed buffers (the engine
                    # falls through to the per-call path for sync/aio)
                    pool=(self._staging_pool.native_pool
                          if self._staging_pool is not None
                          and not self._staging_pool.broken else None),
                    pool_stats=self._staging_pool)
                if self._staging_pool is not None \
                        and self._staging_pool.native_pool is not None \
                        and cfg.io_engine == "uring":
                    self._staging_pool.note_occupancy(
                        min(cfg.io_depth, self._staging_pool.n_slots))

            try:
                # --ioretries: a transient chunk failure re-issues the
                # whole chunk (accounting only books after success, so
                # nothing double-counts; the re-run is idempotent I/O)
                self._retrying_native(call)
            except NativeVerifyError as err:
                file_off = int(offsets[err.block_idx]) + err.word_idx * 8
                raise WorkerException(
                    f"data integrity check failed at file offset "
                    f"{file_off}: expected {err.want:#x}, "
                    f"got {err.got:#x}"
                    + self._verify_fail_hint(err.got)) from None

        while True:
            batch = gen.next_batch(chunk)
            if batch is None:
                break
            submit(batch[0], batch[1])
        return True

    def _buf_addr(self) -> int:
        return self._staging_pool.slot_addrs[0]

    def rotated_staging_buf(self) -> memoryview:
        """The staging slot serving the NEXT op under the worker's
        rotation discipline — the shared hand-out point of the S3/GCS,
        HDFS and tpubench families (the POSIX loops rotate inline).
        Books the hand-out in the pool's reuse accounting."""
        buf = self._io_bufs[self._num_iops_submitted % len(self._io_bufs)]
        if self._staging_pool is not None:
            self._staging_pool.account_ops(1)
        return buf

    def _rwmix_read_flags(self, n: int) -> "np.ndarray | None":
        """Per-op rwmix read flags for the next n ops of a write phase —
        the vectorized form of _rwmix_decides_read, bit-identical to the
        engine's (rwmix_base + block_idx) % 100 sequence."""
        pct = self.cfg.rwmix_read_pct
        if not pct:
            return None
        base = np.uint64(self.rank + self._num_iops_submitted)
        return (((base + np.arange(n, dtype=np.uint64)) % np.uint64(100))
                < np.uint64(pct)).astype(np.uint8)

    def _flock_mode_code(self) -> int:
        """--flock mode for the engine: 0 none, 1 range, 2 full."""
        return {"": 0, "range": 1, "full": 2}[self.cfg.use_file_locks]

    def _block_var_seed(self) -> int:
        """Variance-refill seed, varied per worker and per chunk."""
        return (self.rank << 32) ^ self._num_iops_submitted

    @staticmethod
    def _verify_fail_hint(got: int) -> str:
        """An all-zero mismatch usually means an unwritten/sparse region
        was read (e.g. rwmix reads against a file still being created),
        not on-disk corruption — say so instead of crying corruption."""
        return (" (read of an unwritten/sparse region?)"
                if got == 0 else "")

    def _rwmix_decides_read(self) -> bool:
        """Per-op modulo split (reference: (workerRank+numIOPSSubmitted)%100
        < rwMixReadPercent, LocalWorker.cpp:1741-1742)."""
        pct = self.cfg.rwmix_read_pct
        if not pct:
            return False
        return (self.rank + self._num_iops_submitted) % 100 < pct

    # -- write-side block content -------------------------------------------

    def _pre_write_fill(self, buf: memoryview, offset: int,
                        length: int) -> None:
        cfg = self.cfg
        if self._tpu is not None:
            # TPU staging: block content originates in HBM; device->host
            # transfer lands it in the write buffer (replaces cudaMemcpy
            # D2H pre-write, reference LocalWorker.cpp:2437-2490). With
            # --verify the pattern itself is generated on-device so the
            # read-back check still holds. Guarded: a device loss here
            # triggers --tpufallback failover instead of a bare abort.
            self._tpu_guarded(self._tpu.device_to_host, buf, length,
                              verify_salt=cfg.integrity_check_salt,
                              file_offset=offset)
            self._sync_tpu_usec()
            self.tpu_transfer_bytes += length
            return
        if cfg.integrity_check_salt:
            self._fill_verify_pattern(buf, offset, length,
                                      cfg.integrity_check_salt)
        elif cfg.block_variance_pct:
            refill = (length * cfg.block_variance_pct) // 100
            if refill:
                buf[:refill] = self._block_var_algo.fill_buffer(refill)

    @staticmethod
    def _fill_verify_pattern(buf: memoryview, offset: int, length: int,
                             salt: int) -> None:
        """Each 8-byte-aligned word = (file offset of word + salt)
        (reference: preWriteIntegrityCheckFillBuf, LocalWorker.cpp:2124)."""
        n_words = length // 8
        arr = np.frombuffer(buf[:n_words * 8], dtype=np.uint64)
        with np.errstate(over="ignore"):
            arr[:] = (np.arange(n_words, dtype=np.uint64) * np.uint64(8)
                      + np.uint64(offset) + np.uint64(salt))
        tail = length - n_words * 8
        if tail:
            buf[n_words * 8:length] = bytes(tail)

    def _verify_read_buf(self, buf: memoryview, offset: int,
                         length: int) -> None:
        """memcmp + exact mismatch offset report (reference:
        postReadIntegrityCheckVerifyBuf, LocalWorker.cpp:2170)."""
        salt = self.cfg.integrity_check_salt
        n_words = length // 8
        got = np.frombuffer(buf[:n_words * 8], dtype=np.uint64)
        with np.errstate(over="ignore"):
            want = (np.arange(n_words, dtype=np.uint64) * np.uint64(8)
                    + np.uint64(offset) + np.uint64(salt))
        bad = np.nonzero(got != want)[0]
        if bad.size:
            first = int(bad[0])
            raise WorkerException(
                f"data integrity check failed at file offset "
                f"{offset + first * 8}: expected {int(want[first]):#x}, "
                f"got {int(got[first]):#x}")

    # -- read-side block actions --------------------------------------------

    def _post_read_actions(self, buf: memoryview, offset: int,
                           length: int) -> None:
        cfg = self.cfg
        if self._tpu is not None:
            # host->HBM DMA of the read block (replaces cudaMemcpy H2D post-
            # read / cuFile read, reference LocalWorker.cpp:2633-2749);
            # guarded for --tpufallback chip failover
            self._tpu_guarded(self._tpu.host_to_device, buf, length,
                              verify_salt=cfg.integrity_check_salt
                              if cfg.do_tpu_verify else 0,
                              file_offset=offset)
            self._sync_tpu_usec()
            self.tpu_transfer_bytes += length
            # host-staging failover clears verify_on_device, so a
            # degraded phase falls through to the host memcmp below
            if cfg.do_tpu_verify and cfg.integrity_check_salt \
                    and self._tpu.verify_on_device:
                return  # verified on-device by the Pallas kernel
        if cfg.integrity_check_salt:
            self._verify_read_buf(buf, offset, length)

    def _inline_read_back(self, fd: int, buf: memoryview, offset: int,
                          length: int) -> None:
        """--readinline/--verifydirect: read back immediately after write
        (reference: pwriteAndReadWrapper, LocalWorker.cpp:2566)."""
        n = os.preadv(fd, [buf[:length]], offset)
        if n != length:
            raise WorkerException(f"short inline read-back at {offset}")
        if self.cfg.integrity_check_salt:
            self._verify_read_buf(buf, offset, length)

    # ------------------------------------------------------------------
    # mmap I/O path (reference: mmap wrappers, LocalWorker.cpp:2534+)
    # ------------------------------------------------------------------

    def _rw_block_sized_mmap(self, fd: int, is_write: bool,
                             gen=None) -> None:
        cfg = self.cfg
        size = cfg.file_size
        if is_write and stat_mod.S_ISREG(os.fstat(fd).st_mode):
            os.ftruncate(fd, size)  # block devices keep their size
        prot = mmap.PROT_WRITE | mmap.PROT_READ if is_write else mmap.PROT_READ
        mapped = mmap.mmap(fd, size, prot=prot)
        try:
            self._apply_madvise(mapped)
            if gen is None:
                gen = self._make_offset_gen_for_file(is_write)
            from ..utils.native import get_native_engine
            native = get_native_engine()
            if self._native_loop_eligible(native):
                self._run_native_mmap_loop(native, mapped, gen, is_write)
                return
            for off, length in gen:
                self.check_interruption_request()
                buf = self.rotated_staging_buf()
                t0 = time.perf_counter_ns()
                if is_write:
                    self._pre_write_fill(buf, off, length)
                    mapped[off:off + length] = buf[:length]
                else:
                    buf[:length] = mapped[off:off + length]
                lat_usec = (time.perf_counter_ns() - t0) // 1000
                if not is_write:
                    self._post_read_actions(buf, off, length)
                self.iops_latency_histo.add_latency(lat_usec)
                self.live_ops.num_bytes_done += length
                self.live_ops.num_iops_done += 1
                self._num_iops_submitted += 1
            if self._tpu is not None:
                self._tpu_guarded(self._tpu.flush)
                self._sync_tpu_usec()
        finally:
            mapped.close()

    def _run_native_mmap_loop(self, native, mapped, gen, is_write) -> None:
        """Chunked C++ memcpy loop over the mapping (the --mmap analogue
        of _run_native_block_loop; same eligibility and block-modifier
        handling)."""
        from ..utils.native import NativeVerifyError
        cfg = self.cfg
        # np.frombuffer works for read-only PROT_READ mappings too (ctypes
        # from_buffer would demand writability); the address stays valid
        # while `mapped` is open
        map_addr = np.frombuffer(mapped, dtype=np.uint8).ctypes.data
        chunk = self._native_chunk_blocks()
        while True:
            batch = gen.next_batch(chunk)
            if batch is None:
                break
            self.check_interruption_request(force=True)
            offsets, lengths = batch
            flags = self._rwmix_read_flags(len(offsets)) if is_write \
                else None
            try:
                native.run_mmap_loop(
                    map_addr, offsets, lengths, is_write,
                    buf_addr=self._buf_addr(), worker=self,
                    interrupt_flag=self._native_interrupt,
                    op_is_read=flags,
                    verify_salt=cfg.integrity_check_salt,
                    block_var_pct=cfg.block_variance_pct,
                    block_var_seed=self._block_var_seed(),
                    limit_read_bps=cfg.limit_read_bps,
                    limit_write_bps=cfg.limit_write_bps,
                    rl_state=self._native_rl_state)
            except NativeVerifyError as err:
                # mmap reads of unwritten sparse regions memcpy zeros (no
                # short-read signal like the pread loops) — the hint below
                # covers that case
                file_off = int(offsets[err.block_idx]) + err.word_idx * 8
                raise WorkerException(
                    f"data integrity check failed at file offset "
                    f"{file_off}: expected {err.want:#x}, "
                    f"got {err.got:#x}"
                    + self._verify_fail_hint(err.got)) from None

    def _apply_madvise(self, mapped: mmap.mmap) -> None:
        flags_str = self.cfg.madvise_flags
        if not flags_str:
            return
        advice_map = {"seq": mmap.MADV_SEQUENTIAL,
                      "rand": mmap.MADV_RANDOM,
                      "willneed": mmap.MADV_WILLNEED,
                      "dontneed": mmap.MADV_DONTNEED,
                      # reference: ARG_MADVISE_FLAG_{,NO}HUGEPAGE_NAME
                      "hugepage": getattr(mmap, "MADV_HUGEPAGE", 14),
                      "nohugepage": getattr(mmap, "MADV_NOHUGEPAGE", 15)}
        for name in flags_str.split(","):
            name = name.strip()
            if not name:
                continue
            if name not in advice_map:
                raise WorkerException(f"unknown madvise flag: {name}")
            mapped.madvise(advice_map[name])

    # ------------------------------------------------------------------
    # file/bdev mode (reference: fileModeIterateFilesSeq :3597,
    # fileModeIterateFilesRand :3511, fileModeDeleteFiles :3769)
    # ------------------------------------------------------------------

    def _file_mode_phase(self, phase: BenchPhase) -> None:
        cfg = self.cfg
        if phase == BenchPhase.DELETEFILES:
            # workers round-robin the given files (reference :3769)
            for i, p in enumerate(cfg.paths):
                if i % cfg.num_dataset_threads == \
                        (self.rank % cfg.num_dataset_threads):
                    try:
                        os.unlink(p)
                    except FileNotFoundError:
                        if not cfg.ignore_delete_errors \
                                and not self._partial_tolerance(phase):
                            raise
                    self.live_ops.num_entries_done += 1
            return
        if phase == BenchPhase.STATFILES:
            for p in cfg.paths:
                os.stat(p)
                self.live_ops.num_entries_done += 1
            return

        is_write = (phase == BenchPhase.CREATEFILES)
        num_files = len(cfg.paths)
        total_range = cfg.file_size * num_files

        gen = self._make_file_mode_offset_gen(is_write, total_range)
        if gen is None:
            self.got_phase_work = False
            return
        if is_write and cfg.do_truncate_to_size:
            for fd in self._path_fds:
                os.ftruncate(fd, cfg.file_size)
        if cfg.use_mmap and num_files == 1:
            # file/bdev mode via memory mapping (reference: prepareMmapVec,
            # ProgArgs.cpp:2109); worker's share drives the same gen
            self._rw_block_sized_mmap(self._path_fds[0], is_write, gen=gen)
            return
        # single file/bdev: global offsets ARE in-file offsets; striped
        # multi-file passes the (fds, file_size) mapping — the native C++
        # engine takes the hot loop in both shapes, the Python fallback
        # derives its per-block mapping from the same stripe tuple
        # (reference: calcFileIdxAndOffsetStriped, LocalWorker.cpp:2084)
        self._rw_block_sized(
            self._path_fds[0], gen, is_write,
            stripe=(list(self._path_fds), cfg.file_size)
            if num_files > 1 else None)

    def _make_file_mode_offset_gen(self, is_write: bool, total_range: int):
        """Per-worker share of the shared file/bdev range: seq mode slices a
        contiguous range per dataset thread; rand mode divides randamount;
        --strided interleaves blocks (reference: initPhaseRWOffsetGen +
        SURVEY.md section 2.4 "Shared-file striping")."""
        cfg = self.cfg
        bs = cfg.block_size
        ndst = max(1, cfg.num_dataset_threads)
        rank = self.rank % ndst
        if cfg.use_random_offsets:
            amount_total = cfg.random_amount or total_range
            amount = amount_total // ndst
            if amount < bs:
                return None
            if cfg.no_random_align:
                return OffsetGenRandom(self._rand_offset_algo, amount, bs,
                                       range_len=total_range)
            if is_write:
                return OffsetGenRandomAlignedFullCoverage(
                    self._rand_offset_algo, amount, bs, range_len=total_range)
            return OffsetGenRandomAligned(self._rand_offset_algo, amount, bs,
                                          range_len=total_range)
        if cfg.do_strided_access:
            num_blocks = total_range // bs
            blocks_per_worker = num_blocks // ndst + \
                (1 if rank < num_blocks % ndst else 0)
            if not blocks_per_worker:
                return None
            return OffsetGenStrided(blocks_per_worker * bs, bs, rank, ndst)
        # sequential contiguous slice per dataset thread
        slice_len = total_range // ndst
        slice_start = rank * slice_len
        if rank == ndst - 1:
            slice_len = total_range - slice_start  # last takes remainder
        if not slice_len:
            return None
        if not is_write and cfg.shuffle_window:
            # shared-file shape: each worker permutes its own
            # contiguous slice with the common epoch+rank seed
            return self._make_shuffle_gen(slice_len, start=slice_start)
        if cfg.do_reverse_seq_offsets:
            return OffsetGenReverseSeq(slice_len, bs, start=slice_start)
        return OffsetGenSequential(slice_len, bs, start=slice_start)

    # ------------------------------------------------------------------
    # custom tree mode (reference: dirModeIterateCustomDirs :2960/:3294)
    # ------------------------------------------------------------------

    def _custom_tree_iterate_files(self, phase: BenchPhase) -> None:
        from ..toolkits.path_store import PathStore
        cfg = self.cfg
        store = PathStore(block_size=cfg.block_size)
        if phase in (BenchPhase.CREATEDIRS, BenchPhase.DELETEDIRS):
            store.load_dirs_from_file(cfg.tree_file_path)
        else:
            store.load_files_from_file(cfg.tree_file_path,
                                       round_up_size=cfg.tree_round_up_size)
        if cfg.use_custom_tree_rand:
            store.random_shuffle(seed=42)  # same order on all hosts
        else:
            store.sort_by_path_len()
        ndst = max(1, cfg.num_dataset_threads)
        rank = self.rank % ndst
        non_shared, shared = store.split_by_share_size(
            cfg.file_share_size or (cfg.block_size * ndst))
        my_files = non_shared.get_worker_sublist_non_shared(rank, ndst).elems
        if cfg.use_custom_tree_round_robin:
            my_files += shared.get_worker_sublist_shared_round_robin(
                rank, ndst).elems
        else:
            my_files += shared.get_worker_sublist_shared(rank, ndst).elems
        base = cfg.paths[0]
        if phase == BenchPhase.DELETEFILES:
            # only one worker deletes a shared file (the slice at offset
            # 0); skipped slices are no phase work — identical accounting
            # on the native and fallback paths
            my_files = [e for e in my_files if e.range_start == 0]
            if not my_files:
                self.got_phase_work = False
                return
        from ..utils.native import get_native_engine
        native = get_native_engine()
        if self._can_use_native_file_loop(native, phase):
            self._run_native_tree_loop(native, phase, base, my_files)
            return
        for elem in my_files:
            self.check_interruption_request(force=True)
            path = os.path.join(base, elem.path)
            t0 = time.perf_counter_ns()
            if phase == BenchPhase.CREATEFILES:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                fd = os.open(path, self._open_flags_write(), MKFILE_MODE)
                try:
                    if elem.range_len:
                        gen = OffsetGenSequential(elem.range_len,
                                                 cfg.block_size,
                                                 start=elem.range_start)
                        self._rw_block_sized(fd, gen, is_write=True)
                finally:
                    os.close(fd)
            elif phase == BenchPhase.READFILES:
                flags = os.O_RDONLY | (os.O_DIRECT if cfg.use_direct_io else 0)
                fd = os.open(path, flags)
                try:
                    if elem.range_len:
                        gen = OffsetGenSequential(elem.range_len,
                                                 cfg.block_size,
                                                 start=elem.range_start)
                        self._rw_block_sized(fd, gen, is_write=False)
                finally:
                    os.close(fd)
            elif phase == BenchPhase.STATFILES:
                os.stat(path)
            elif phase == BenchPhase.DELETEFILES:
                try:  # non-zero shared slices were filtered out above
                    os.unlink(path)
                except FileNotFoundError:
                    if not cfg.ignore_delete_errors \
                            and not self._partial_tolerance(phase):
                        raise
            lat_usec = (time.perf_counter_ns() - t0) // 1000
            self.entries_latency_histo.add_latency(lat_usec)
            self.live_ops.num_entries_done += 1

    def _run_native_tree_loop(self, native, phase: BenchPhase, base: str,
                              my_files) -> None:
        """Custom-tree files through the C++ file loop with per-file byte
        ranges (shared-file slices keep their [range_start, range_len))."""
        cfg = self.cfg
        op = self._NATIVE_FILE_OPS[phase]
        if phase == BenchPhase.CREATEFILES:
            open_flags = self._open_flags_write()
            # dirs are created up front (the reference pre-creates the
            # tree's dirs in their own phase; mkdir is not per-file work)
            for d in {os.path.dirname(os.path.join(base, e.path))
                      for e in my_files}:
                os.makedirs(d or ".", exist_ok=True)
        else:
            open_flags = os.O_RDONLY | (os.O_DIRECT if cfg.use_direct_io
                                        else 0)
        paths: "list[str]" = []
        starts: "list[int]" = []
        lens: "list[int]" = []
        chunk_bytes = 0

        from ..utils.native import NativeVerifyError

        def submit():
            self.check_interruption_request(force=True)

            def call(paths=paths, starts=starts, lens=lens):
                native.run_file_loop(
                    paths, op, open_flags, cfg.file_size, cfg.block_size,
                    buf_addr=self._buf_addr() if self._io_bufs else 0,
                    ignore_delete_errors=cfg.ignore_delete_errors
                    or self._partial_tolerance(phase),
                    worker=self, interrupt_flag=self._native_interrupt,
                    ranges=(starts, lens) if op in ("write", "read")
                    else None,
                    verify_salt=cfg.integrity_check_salt,
                    block_var_pct=cfg.block_variance_pct,
                    block_var_seed=self._block_var_seed(),
                    rwmix_pct=cfg.rwmix_read_pct
                    if phase == BenchPhase.CREATEFILES else 0,
                    limit_read_bps=cfg.limit_read_bps,
                    limit_write_bps=cfg.limit_write_bps,
                    rl_state=self._native_rl_state,
                    inline_readback=(cfg.do_read_inline
                                     or cfg.do_direct_verify),
                    flock_mode=self._flock_mode_code())

            try:
                self._retrying_native(call, retryable=op != "unlink")
            except NativeVerifyError as err:
                # map the global block index back through the per-file
                # [range_start, range_len) slices
                blk = err.block_idx
                hint = self._verify_fail_hint(err.got)
                for path, r_start, r_len in zip(paths, starts, lens):
                    # zero-length files contribute zero blocks, exactly
                    # like the engine's per-file block count
                    n_blocks = (r_len + cfg.block_size - 1) \
                        // cfg.block_size
                    if blk < n_blocks:
                        off = r_start + blk * cfg.block_size \
                            + err.word_idx * 8
                        raise WorkerException(
                            f"data integrity check failed at file offset "
                            f"{off} of {path}: expected {err.want:#x}, "
                            f"got {err.got:#x}{hint}") from None
                    blk -= n_blocks
                raise WorkerException(
                    f"data integrity check failed (block {err.block_idx}): "
                    f"expected {err.want:#x}, got {err.got:#x}{hint}"
                ) from None

        for elem in my_files:
            paths.append(os.path.join(base, elem.path))
            starts.append(elem.range_start)
            lens.append(elem.range_len)
            chunk_bytes += elem.range_len
            if len(paths) >= self._NATIVE_CHUNK_MAX_BLOCKS \
                    or chunk_bytes >= self._NATIVE_CHUNK_MAX_BYTES:
                submit()
                paths, starts, lens, chunk_bytes = [], [], [], 0
        if paths:
            submit()

    # ------------------------------------------------------------------
    # sync / dropcaches (reference: anyModeSync :8075 / DropCaches :8118)
    # ------------------------------------------------------------------

    def _any_mode_sync(self) -> None:
        """Only the first worker syncs; others report no phase work."""
        if self.rank % max(1, self.cfg.num_threads) != 0:
            self.got_phase_work = False
            return
        os.sync()
        self.live_ops.num_entries_done += 1

    def _any_mode_drop_caches(self) -> None:
        if self.rank % max(1, self.cfg.num_threads) != 0:
            self.got_phase_work = False
            return
        try:
            with open("/proc/sys/vm/drop_caches", "w") as f:
                f.write("3")
        except PermissionError as err:
            raise WorkerException(
                "dropping caches requires root privileges") from err
        self.live_ops.num_entries_done += 1
