from .shared import (WorkerException, WorkerInterruptedException,  # noqa: F401
                     WorkersSharedData)
from .base import Worker  # noqa: F401
from .local_worker import LocalWorker  # noqa: F401
from .manager import WorkerManager  # noqa: F401
