"""Shared phase state between worker threads and the coordinator.

Reference: source/workers/WorkersSharedData.{h,cpp} — one mutex+condvar, the
current bench phase, the **bench UUID** acting as the phase-start signal,
done counters, phase start timestamps, CPU-util snapshots at stonewall and
last-done, and interrupt/time-limit flags (WorkersSharedData.h:33-107).
Also the worker exception types (source/workers/WorkerException.h).
"""

from __future__ import annotations

import threading
import time
import uuid as uuid_mod

from ..phases import BenchPhase
from ..stats.cpu_util import CPUUtil


class WorkerException(Exception):
    """Fatal worker error; coordinator interrupts everything (fail-fast,
    SURVEY.md section 5.3)."""


class WorkerInterruptedException(Exception):
    """Raised inside a worker when interruption was requested."""


class WorkerRemoteException(WorkerException):
    """Error reported by a remote service instance."""


class WorkerStalledException(WorkerRemoteException):
    """The --svcstalledsecs watchdog declared a remote host stalled: its
    live counters stopped advancing (or it stopped answering /status)
    for longer than the configured window."""


class WorkerHijackedException(WorkerRemoteException):
    """A /status reply carried an unexpected bench UUID: another master
    took over the service. Always a hard abort — never degraded
    (reference: RemoteWorker.cpp:199-202)."""


class WorkersSharedData:
    def __init__(self, config):
        self.config = config
        self.cond = threading.Condition()
        self.current_phase: BenchPhase = BenchPhase.IDLE
        self.bench_uuid: str = ""
        self.phase_start_monotonic: float = 0.0
        self.phase_start_wall: float = 0.0
        self.num_workers_done = 0
        self.num_workers_done_with_error = 0
        # --svctolerant: hosts lost mid-run and dropped from the barrier;
        # persists across phases (a lost host stays lost for the run)
        self.num_workers_degraded = 0
        self.degraded_hosts: "list[str]" = []
        self.stonewall_triggered = False
        self.interrupt_requested = False
        self.phase_time_expired = False
        # --tpufallback: chips declared lost by a worker's failover; a
        # dead chip stays dead for the run, and sibling workers consult
        # this set when picking a failover target
        self.poisoned_tpu_chips: "set[int]" = set()
        # latched when a write phase ends interrupted/errored: later
        # delete phases then tolerate missing entries (a partial dataset
        # is EXPECTED after an aborted write — raising FileNotFoundError
        # noise over it would fail the cleanup the user asked for)
        self.partial_dataset = False
        self.cpu_util = CPUUtil()
        self.cpu_util_stonewall: float = 0.0
        self.cpu_util_last_done: float = 0.0
        self.first_error: "Exception | None" = None
        # --tracefile: the per-process span ring all workers record into
        # (None when tracing is off — instrumentation stays no-op)
        from ..telemetry.tracer import make_tracer
        self.tracer = make_tracer(config)
        if self.tracer is not None \
                and not getattr(config, "run_as_service", False):
            # fleet tracing: the master/local coordinator mints the run
            # trace id; services only ever echo the one stamped onto
            # their requests (span-context propagation)
            self.tracer.extra_other_data["traceId"] = uuid_mod.uuid4().hex
        # --svcstream: master-side streaming control plane bookkeeping
        # (tree plan + per-host live states fed by root stream readers);
        # None = per-request polling, byte-for-byte parity
        self.stream_control = None
        if getattr(config, "svc_stream", False) \
                and getattr(config, "hosts", None) \
                and not getattr(config, "run_as_service", False):
            from ..service.stream import StreamControl
            self.stream_control = StreamControl(config, config.hosts)
        # --rwmixthrpct byte-ratio balancer, shared by all workers
        # (reference: RateLimiterRWMixThreads static atomics)
        self.rwmix_balancer = None
        if getattr(config, "rwmix_thr_read_pct", 0):
            from ..toolkits.rate_limiter import RateLimiterRWMixThreads
            self.rwmix_balancer = RateLimiterRWMixThreads(
                config.rwmix_thr_read_pct)

    # -- phase control (coordinator side) -----------------------------------

    def start_phase(self, phase: BenchPhase,
                    bench_uuid: str = "") -> str:
        """Set new phase + fresh bench UUID and wake all workers
        (reference: WorkerManager::startNextPhase, WorkerManager.cpp:292).
        ``bench_uuid`` forces a specific UUID instead of minting one:
        master runs pre-mint the UUID so it can be journaled before
        /startphase, and a --resume --adopt takeover re-presents the
        dead master's UUID so the fleet's duplicate-start idempotency
        keeps the in-flight phase running instead of restarting it."""
        with self.cond:
            # latch BEFORE the flags reset: a write phase that ended via
            # --timelimit expiry, an interrupt, or a worker error left a
            # partial dataset behind — the delete phases of this run must
            # tolerate the files that were never created
            if self.current_phase == BenchPhase.CREATEFILES and (
                    self.phase_time_expired or self.interrupt_requested
                    or self.num_workers_done_with_error):
                self.partial_dataset = True
            self.current_phase = phase
            self.bench_uuid = bench_uuid or str(uuid_mod.uuid4())
            self.num_workers_done = 0
            self.num_workers_done_with_error = 0
            self.stonewall_triggered = False
            self.phase_time_expired = False
            self.phase_start_monotonic = time.monotonic()
            self.phase_start_wall = time.time()
            self.cpu_util.update()  # baseline for phase CPU util
            if self.rwmix_balancer is not None:
                self.rwmix_balancer.reset()
            self.cond.notify_all()
            return self.bench_uuid

    def adopt_bench_uuid(self, bench_id: str) -> None:
        """Replace the locally-minted phase UUID with the master's
        (service-side /startphase: the master's UUID wins so the hijack
        check compares against what the master believes). Under the
        condition lock like every bench_uuid transition — workers block
        in wait_for_phase_change comparing this field."""
        with self.cond:
            self.bench_uuid = bench_id
            self.cond.notify_all()

    def mark_phase_time_expired(self) -> None:
        """Latch --timelimit expiry. Reentrant-safe under the condition
        lock (threading.Condition wraps an RLock), so callers already
        holding self.cond — the done-wait loop — can use it too."""
        with self.cond:
            self.phase_time_expired = True
            self.cond.notify_all()

    def clear_bench_uuid(self) -> None:
        """Forget the current master's run id. Used by the service-side
        lease watchdog after orphan recovery (--svcleasesecs): the next
        /startphase from any master must look like a fresh run, never a
        duplicate-start of the orphaned one."""
        with self.cond:
            self.bench_uuid = ""
            self.cond.notify_all()

    def mark_partial_dataset(self) -> None:
        """Latch the partial-dataset tolerance up front. A --resume run
        whose journal shows an unfinished write phase re-runs it over
        whatever the interrupted run left on disk — delete/overwrite of
        missing entries is expected there, exactly like after an in-run
        aborted write."""
        with self.cond:
            self.partial_dataset = True

    # -- worker side --------------------------------------------------------

    def wait_for_phase_change(self, last_uuid: str) -> "tuple[BenchPhase, str]":
        with self.cond:
            while self.bench_uuid == last_uuid:
                self.cond.wait()
            return self.current_phase, self.bench_uuid

    def inc_num_workers_done(self) -> None:
        """First finisher triggers the stonewall: all still-running workers
        snapshot their stats for the "first done" result column
        (reference: WorkersSharedData done counters + TriggerStoneWall)."""
        with self.cond:
            self.num_workers_done += 1
            if not self.stonewall_triggered:
                self.stonewall_triggered = True
                self.cpu_util_stonewall = self.cpu_util.update()
            self.cond.notify_all()

    def inc_num_workers_done_with_error(self, err: Exception) -> None:
        with self.cond:
            if self.first_error is None:
                self.first_error = err
            self.num_workers_done_with_error += 1
            self.cond.notify_all()

    def try_degrade_worker(self, worker, err: Exception) -> bool:
        """--svctolerant N: drop a failed remote host from the done-barrier
        accounting instead of failing the run, as long as at most N hosts
        have been lost. Returns True when the worker was degraded (its
        thread must exit); False keeps today's fail-fast behavior.

        Deliberately NOT a stonewall trigger and NOT an error count: a
        degraded phase completes with the survivors, and the results are
        marked via degraded_hosts so a degraded number can never
        masquerade as a clean one (stats/statistics.py)."""
        tolerant = getattr(self.config, "svc_tolerant_hosts", 0)
        host = getattr(worker, "host", None)
        if tolerant <= 0 or host is None:
            return False
        with self.cond:
            # accounting is per WORKER, not per host string: with a
            # duplicated --hosts entry each worker must still draw from
            # the tolerance cap and bump the barrier count, or the
            # done-barrier never completes
            if not worker.degraded:
                if self.num_workers_degraded >= tolerant:
                    return False
                self.degraded_hosts.append(host)
                self.num_workers_degraded += 1
                worker.degraded = True
            worker.got_phase_work = False
            self.cond.notify_all()
        return True

    # -- interruption -------------------------------------------------------

    def request_interrupt(self) -> None:
        with self.cond:
            self.interrupt_requested = True
            self.cond.notify_all()

    def clear_interrupt(self) -> None:
        with self.cond:
            self.interrupt_requested = False
