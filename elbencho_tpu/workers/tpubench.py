"""TPUBENCH: transfer benchmark over the device fabric (no storage).

The TPU-native analogue of the reference's raw-TCP netbench (SURVEY.md
section 2.3: "netbench analogue can target ICI"): instead of client/server
sockets, workers hammer the data paths a TPU ingest pipeline actually uses:

  h2d   host buffer -> HBM DMA            (cudaMemcpy H2D analogue)
  d2h   HBM -> host buffer DMA            (cudaMemcpy D2H analogue)
  both  h2d followed by d2h per block     (request/response analogue)
  ici   ring ppermute of a sharded array over every chip of the mesh —
        each step moves the full shard over the inter-chip interconnect
        (the XLA-collective replacement for NCCL-style p2p benchmarks)
  allgather / reducescatter / alltoall / psum
        the remaining collective families a sharded ingest pipeline
        exercises (all_gather fan-in, reduce_scatter fan-out, all-to-all
        reshards, psum trees), each as its own timed pattern so per-op
        fabric latency is attributable per collective — the NCCL
        perf-test suite analogue, on XLA collectives

Workers transfer --size bytes total in --block chunks; per-op latency goes
to the IOPS histogram; bytes count into both live ops and the per-chip HBM
ingest accounting. Runs on one chip (h2d/d2h/both; ici degenerates to a
self-permute) and scales to a full pod slice.
"""

from __future__ import annotations

import time

from ..phases import BenchPhase
from .shared import WorkerException


COLLECTIVE_PATTERNS = ("ici", "allgather", "reducescatter", "alltoall",
                       "psum")
TRANSFER_PATTERNS = ("h2d", "d2h", "both")


def run_tpubench_phase(worker, phase: BenchPhase) -> None:
    cfg = worker.cfg
    pattern = cfg.tpu_bench_pattern
    if worker._tpu is None:
        raise WorkerException(
            "--tpubench requires --tpuids (chips to benchmark)")
    if pattern in COLLECTIVE_PATTERNS:
        _run_collective(worker, pattern)
        return
    if pattern not in TRANSFER_PATTERNS:
        raise WorkerException(
            f"unknown --tpubenchpat {pattern!r} "
            f"({'|'.join(TRANSFER_PATTERNS + COLLECTIVE_PATTERNS)})")
    ctx = worker._tpu
    bs = cfg.block_size
    total = max(cfg.file_size, bs)
    done = 0
    while done < total:
        worker.check_interruption_request()
        length = min(bs, total - done)
        buf = worker.rotated_staging_buf()
        t0 = time.perf_counter_ns()
        if pattern in ("h2d", "both"):
            ctx.host_to_device(buf, length)
        if pattern in ("d2h", "both"):
            ctx.device_to_host(buf, length)
        lat_usec = (time.perf_counter_ns() - t0) // 1000
        moved = length * (2 if pattern == "both" else 1)
        worker.iops_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_bytes_done += moved
        worker.live_ops.num_iops_done += 1
        worker.tpu_transfer_bytes += moved
        worker._num_iops_submitted += 1
        done += length
        # split accounting from the pipeline: host-side dispatch cost vs
        # DMA wall time (the per-op histogram above times the full call);
        # synced per op so an interrupt mid-window keeps partial stats
        worker.tpu_dispatch_usec = ctx.dispatch_usec
        worker.tpu_transfer_usec = ctx.transfer_usec
    ctx.flush()  # drain the in-flight window; --tpubudget checks here
    worker.tpu_dispatch_usec = ctx.dispatch_usec
    worker.tpu_transfer_usec = ctx.transfer_usec


def _select_collective_devices(cfg, jax) -> list:
    """Devices for the collective mesh. Single-process runs honor the
    --tpuids subset (chip indices into jax.devices(), modulo, deduped);
    multi-process SPMD requires every process to build the SAME global
    mesh over every chip, so there --tpuids is ignored with a NOTE."""
    from ..toolkits.logger import LOG_NORMAL, log
    all_devices = list(jax.devices())
    if not cfg.tpu_ids:
        return all_devices
    if jax.process_count() > 1:
        log(LOG_NORMAL,
            "NOTE: --tpuids is ignored for collective --tpubench patterns "
            "in a multihost run: the SPMD mesh must span every chip of "
            "the pod slice on every process")
        return all_devices
    selected = []
    for chip_id in cfg.tpu_ids:
        dev = all_devices[chip_id % len(all_devices)]
        if dev not in selected:
            selected.append(dev)
    if len(selected) != len(all_devices):
        log(LOG_NORMAL,
            f"NOTE: collective mesh restricted to {len(selected)} of "
            f"{len(all_devices)} chips (--tpuids)")
    return selected


class CollectiveBench:
    """Jitted one-collective-per-step benchmark over a 1D chip mesh —
    the worker-independent core of the collective patterns, so the same
    step the --tpubench phase times can be driven by the multihost tests
    and the driver's multichip dryrun (round-2 verdict item 3: the
    collective suite never crossed a real process boundary).

    Accounted bytes per step are the sharded array's total size (the
    NCCL-perf-test "algorithm bytes" convention), so patterns are
    directly comparable. In a multi-process runtime every process must
    construct this over the same global device list and call step() in
    lockstep (single SPMD program)."""

    def __init__(self, pattern: str, devices: list, block_size: int):
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from ..parallel.compat import shard_map

        if pattern not in COLLECTIVE_PATTERNS:
            raise ValueError(f"not a collective pattern: {pattern!r}")
        self.pattern = pattern
        n_dev = len(devices)
        mesh = Mesh(np.array(devices), axis_names=("chip",))
        bs_words = max(block_size // 4, 128)
        # all-to-all / reduce-scatter split the lane axis across chips
        bs_words += (-bs_words) % n_dev
        self.block_size_adjusted = bs_words * 4
        self.bytes_per_step = n_dev * bs_words * 4
        # sharded array: one block per chip
        self._arr = jax.device_put(
            np.zeros((n_dev, bs_words), dtype=np.uint32),
            NamedSharding(mesh, P("chip", None)))

        def _per_shard(x):
            if pattern == "ici":  # ring p2p: chips forward their shard
                perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
                return jax.lax.ppermute(x, axis_name="chip", perm=perm)
            if pattern == "allgather":
                r = jax.lax.all_gather(x, "chip").sum(dtype=jnp.uint32)
            elif pattern == "reducescatter":
                r = jax.lax.psum_scatter(
                    x, "chip", scatter_dimension=1, tiled=True) \
                    .sum(dtype=jnp.uint32)
            elif pattern == "alltoall":
                # tiled: the lane axis is cut into one slice per chip and
                # the slices are exchanged (shape-preserving reshard)
                r = jax.lax.all_to_all(
                    x, "chip", split_axis=1, concat_axis=1, tiled=True) \
                    .sum(dtype=jnp.uint32)
            else:  # psum: full-array allreduce
                r = jax.lax.psum(x, "chip").sum(dtype=jnp.uint32)
            # fold the per-shard scalar so the output is replicated
            # (clean P() out spec); negligible next to the collective
            return jax.lax.psum(r, "chip").reshape(())

        self._stateful = pattern == "ici"  # ring permute carries state
        out_spec = P("chip", None) if self._stateful else P()
        self._jit_step = jax.jit(shard_map(
            _per_shard, mesh=mesh, in_specs=P("chip", None),
            out_specs=out_spec, check_replication=False))
        self._block_until_ready = jax.block_until_ready

    def warmup(self) -> None:
        """Compile outside any timed loop."""
        self._block_until_ready(self._jit_step(self._arr))

    def step(self) -> int:
        """One timed collective; returns the latency in usec."""
        t0 = time.perf_counter_ns()
        out = self._jit_step(self._arr)
        self._block_until_ready(out)
        if self._stateful:
            self._arr = out
        return (time.perf_counter_ns() - t0) // 1000


def _run_collective(worker, pattern: str) -> None:
    """Drive CollectiveBench for the phase; only the first local worker
    drives the mesh (one SPMD program per host, like the reference's
    rank-0-only sync phase). Per-step latency goes to the IOPS
    histogram; bytes into live ops + HBM ingest accounting."""
    cfg = worker.cfg
    if worker.rank % max(1, cfg.num_threads) != 0:
        worker.got_phase_work = False
        return
    import jax

    from ..toolkits.logger import LOG_NORMAL, log

    devices = _select_collective_devices(cfg, jax)
    bench = CollectiveBench(pattern, devices, cfg.block_size)
    if bench.block_size_adjusted != cfg.block_size:
        # auto-adjustments are always surfaced (repo convention, e.g. the
        # file-size reduction notes in config/args.py)
        log(LOG_NORMAL,
            f"NOTE: collective block size adjusted to "
            f"{bench.block_size_adjusted} bytes (word-aligned and "
            f"divisible by {len(devices)} chips); accounted bytes per "
            f"step use the adjusted size")
    total = max(cfg.file_size, cfg.block_size)
    bench.warmup()
    done = 0
    while done < total:
        worker.check_interruption_request(force=True)
        lat_usec = bench.step()
        worker.iops_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_bytes_done += bench.bytes_per_step
        worker.live_ops.num_iops_done += 1
        worker.tpu_transfer_bytes += bench.bytes_per_step
        worker.tpu_transfer_usec += lat_usec
        done += bench.bytes_per_step
