"""TPUBENCH: transfer benchmark over the device fabric (no storage).

The TPU-native analogue of the reference's raw-TCP netbench (SURVEY.md
section 2.3: "netbench analogue can target ICI"): instead of client/server
sockets, workers hammer the data paths a TPU ingest pipeline actually uses:

  h2d   host buffer -> HBM DMA            (cudaMemcpy H2D analogue)
  d2h   HBM -> host buffer DMA            (cudaMemcpy D2H analogue)
  both  h2d followed by d2h per block     (request/response analogue)
  ici   ring ppermute of a sharded array over every chip of the mesh —
        each step moves the full shard over the inter-chip interconnect
        (the XLA-collective replacement for NCCL-style p2p benchmarks)
  allgather / reducescatter / alltoall / psum
        the remaining collective families a sharded ingest pipeline
        exercises (all_gather fan-in, reduce_scatter fan-out, all-to-all
        reshards, psum trees), each as its own timed pattern so per-op
        fabric latency is attributable per collective — the NCCL
        perf-test suite analogue, on XLA collectives

Workers transfer --size bytes total in --block chunks; per-op latency goes
to the IOPS histogram; bytes count into both live ops and the per-chip HBM
ingest accounting. Runs on one chip (h2d/d2h/both; ici degenerates to a
self-permute) and scales to a full pod slice.
"""

from __future__ import annotations

import time

from ..phases import BenchPhase
from .shared import WorkerException


COLLECTIVE_PATTERNS = ("ici", "allgather", "reducescatter", "alltoall",
                       "psum")
TRANSFER_PATTERNS = ("h2d", "d2h", "both")


def run_tpubench_phase(worker, phase: BenchPhase) -> None:
    cfg = worker.cfg
    pattern = cfg.tpu_bench_pattern
    if worker._tpu is None:
        raise WorkerException(
            "--tpubench requires --tpuids (chips to benchmark)")
    if pattern in COLLECTIVE_PATTERNS:
        _run_collective(worker, pattern)
        return
    if pattern not in TRANSFER_PATTERNS:
        raise WorkerException(
            f"unknown --tpubenchpat {pattern!r} "
            f"({'|'.join(TRANSFER_PATTERNS + COLLECTIVE_PATTERNS)})")
    ctx = worker._tpu
    bs = cfg.block_size
    total = max(cfg.file_size, bs)
    done = 0
    num_bufs = len(worker._io_bufs)
    while done < total:
        worker.check_interruption_request()
        length = min(bs, total - done)
        buf = worker._io_bufs[worker._num_iops_submitted % num_bufs]
        t0 = time.perf_counter_ns()
        if pattern in ("h2d", "both"):
            ctx.host_to_device(buf, length)
        if pattern in ("d2h", "both"):
            ctx.device_to_host(buf, length)
        lat_usec = (time.perf_counter_ns() - t0) // 1000
        moved = length * (2 if pattern == "both" else 1)
        worker.iops_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_bytes_done += moved
        worker.live_ops.num_iops_done += 1
        worker.tpu_transfer_bytes += moved
        worker.tpu_transfer_usec += lat_usec
        worker._num_iops_submitted += 1
        done += length
    t0 = time.perf_counter_ns()
    ctx.flush()
    worker.tpu_transfer_usec += (time.perf_counter_ns() - t0) // 1000


def _select_collective_devices(cfg, jax) -> list:
    """Devices for the collective mesh. Single-process runs honor the
    --tpuids subset (chip indices into jax.devices(), modulo, deduped);
    multi-process SPMD requires every process to build the SAME global
    mesh over every chip, so there --tpuids is ignored with a NOTE."""
    from ..toolkits.logger import LOG_NORMAL, log
    all_devices = list(jax.devices())
    if not cfg.tpu_ids:
        return all_devices
    if jax.process_count() > 1:
        log(LOG_NORMAL,
            "NOTE: --tpuids is ignored for collective --tpubench patterns "
            "in a multihost run: the SPMD mesh must span every chip of "
            "the pod slice on every process")
        return all_devices
    selected = []
    for chip_id in cfg.tpu_ids:
        dev = all_devices[chip_id % len(all_devices)]
        if dev not in selected:
            selected.append(dev)
    if len(selected) != len(all_devices):
        log(LOG_NORMAL,
            f"NOTE: collective mesh restricted to {len(selected)} of "
            f"{len(all_devices)} chips (--tpuids)")
    return selected


def _run_collective(worker, pattern: str) -> None:
    """One timed collective per step over all available chips; only the
    first local worker drives the mesh (one SPMD program per host, like
    the reference's rank-0-only sync phase).

    Accounted bytes per step are the sharded array's total size
    (the NCCL-perf-test "algorithm bytes" convention), so the patterns
    are directly comparable; per-step latency goes to the IOPS histogram."""
    cfg = worker.cfg
    if worker.rank % max(1, cfg.num_threads) != 0:
        worker.got_phase_work = False
        return
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..parallel.compat import shard_map
    from ..toolkits.logger import LOG_NORMAL, log

    devices = _select_collective_devices(cfg, jax)
    n_dev = len(devices)
    mesh = Mesh(np.array(devices), axis_names=("chip",))
    bs_words = max(cfg.block_size // 4, 128)
    # all-to-all / reduce-scatter split the lane axis across chips
    bs_words += (-bs_words) % n_dev
    if bs_words * 4 != cfg.block_size:
        # auto-adjustments are always surfaced (repo convention, e.g. the
        # file-size reduction notes in config/args.py)
        log(LOG_NORMAL,
            f"NOTE: collective block size adjusted to {bs_words * 4} "
            f"bytes (word-aligned and divisible by {n_dev} chips); "
            f"accounted bytes per step use the adjusted size")
    total = max(cfg.file_size, cfg.block_size)
    # sharded array: one block per chip
    arr = jax.device_put(
        np.zeros((n_dev, bs_words), dtype=np.uint32),
        NamedSharding(mesh, P("chip", None)))

    def _per_shard(x):
        if pattern == "ici":  # ring p2p: every chip forwards its shard
            perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
            return jax.lax.ppermute(x, axis_name="chip", perm=perm)
        if pattern == "allgather":
            r = jax.lax.all_gather(x, "chip").sum(dtype=jnp.uint32)
        elif pattern == "reducescatter":
            r = jax.lax.psum_scatter(
                x, "chip", scatter_dimension=1, tiled=True) \
                .sum(dtype=jnp.uint32)
        elif pattern == "alltoall":
            # tiled: the lane axis is cut into one slice per chip and the
            # slices are exchanged (shape-preserving reshard)
            r = jax.lax.all_to_all(
                x, "chip", split_axis=1, concat_axis=1, tiled=True) \
                .sum(dtype=jnp.uint32)
        else:  # psum: full-array allreduce
            r = jax.lax.psum(x, "chip").sum(dtype=jnp.uint32)
        # fold the per-shard scalar so the output is replicated (clean
        # P() out spec); negligible next to the array collective above
        return jax.lax.psum(r, "chip").reshape(())

    stateful = pattern == "ici"  # the ring permute carries its state
    out_spec = P("chip", None) if stateful else P()
    step = jax.jit(shard_map(
        _per_shard, mesh=mesh, in_specs=P("chip", None),
        out_specs=out_spec, check_replication=False))
    jax.block_until_ready(step(arr))  # warm the compile outside timing
    bytes_per_step = n_dev * bs_words * 4
    done = 0
    while done < total:
        worker.check_interruption_request(force=True)
        t0 = time.perf_counter_ns()
        out = step(arr)
        jax.block_until_ready(out)
        if stateful:
            arr = out
        lat_usec = (time.perf_counter_ns() - t0) // 1000
        worker.iops_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_bytes_done += bytes_per_step
        worker.live_ops.num_iops_done += 1
        worker.tpu_transfer_bytes += bytes_per_step
        worker.tpu_transfer_usec += lat_usec
        done += bytes_per_step
