"""TPUBENCH: transfer benchmark over the device fabric (no storage).

The TPU-native analogue of the reference's raw-TCP netbench (SURVEY.md
section 2.3: "netbench analogue can target ICI"): instead of client/server
sockets, workers hammer the data paths a TPU ingest pipeline actually uses:

  h2d   host buffer -> HBM DMA            (cudaMemcpy H2D analogue)
  d2h   HBM -> host buffer DMA            (cudaMemcpy D2H analogue)
  both  h2d followed by d2h per block     (request/response analogue)
  ici   ring ppermute of a sharded array over every chip of the mesh —
        each step moves the full shard over the inter-chip interconnect
        (the XLA-collective replacement for NCCL-style p2p benchmarks)

Workers transfer --size bytes total in --block chunks; per-op latency goes
to the IOPS histogram; bytes count into both live ops and the per-chip HBM
ingest accounting. Runs on one chip (h2d/d2h/both; ici degenerates to a
self-permute) and scales to a full pod slice.
"""

from __future__ import annotations

import time

from ..phases import BenchPhase
from .shared import WorkerException


def run_tpubench_phase(worker, phase: BenchPhase) -> None:
    cfg = worker.cfg
    pattern = cfg.tpu_bench_pattern
    if worker._tpu is None:
        raise WorkerException(
            "--tpubench requires --tpuids (chips to benchmark)")
    if pattern == "ici":
        _run_ici(worker)
        return
    if pattern not in ("h2d", "d2h", "both"):
        raise WorkerException(
            f"unknown --tpubenchpat {pattern!r} (h2d|d2h|both|ici)")
    ctx = worker._tpu
    bs = cfg.block_size
    total = max(cfg.file_size, bs)
    done = 0
    num_bufs = len(worker._io_bufs)
    while done < total:
        worker.check_interruption_request()
        length = min(bs, total - done)
        buf = worker._io_bufs[worker._num_iops_submitted % num_bufs]
        t0 = time.perf_counter_ns()
        if pattern in ("h2d", "both"):
            ctx.host_to_device(buf, length)
        if pattern in ("d2h", "both"):
            ctx.device_to_host(buf, length)
        lat_usec = (time.perf_counter_ns() - t0) // 1000
        moved = length * (2 if pattern == "both" else 1)
        worker.iops_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_bytes_done += moved
        worker.live_ops.num_iops_done += 1
        worker.tpu_transfer_bytes += moved
        worker.tpu_transfer_usec += lat_usec
        worker._num_iops_submitted += 1
        done += length
    t0 = time.perf_counter_ns()
    ctx.flush()
    worker.tpu_transfer_usec += (time.perf_counter_ns() - t0) // 1000


def _run_ici(worker) -> None:
    """Ring ppermute over all available chips; only the first local worker
    drives the mesh (one SPMD program per host, like the reference's
    rank-0-only sync phase)."""
    cfg = worker.cfg
    if worker.rank % max(1, cfg.num_threads) != 0:
        worker.got_phase_work = False
        return
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ..parallel.compat import shard_map

    devices = jax.devices()
    n_dev = len(devices)
    mesh = Mesh(np.array(devices), axis_names=("chip",))
    bs_words = max(cfg.block_size // 4, 128)
    total = max(cfg.file_size, cfg.block_size)
    # sharded array: one block per chip
    arr = jax.device_put(
        np.zeros((n_dev, bs_words), dtype=np.uint32),
        NamedSharding(mesh, P("chip", None)))

    def _shift(x):
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        return jax.lax.ppermute(x, axis_name="chip", perm=perm)

    step = jax.jit(shard_map(_shift, mesh=mesh, in_specs=P("chip", None),
                             out_specs=P("chip", None)))
    step(arr)[0].block_until_ready()  # warm the compile outside timing
    bytes_per_step = n_dev * bs_words * 4
    done = 0
    while done < total:
        worker.check_interruption_request(force=True)
        t0 = time.perf_counter_ns()
        arr = step(arr)
        jax.block_until_ready(arr)
        lat_usec = (time.perf_counter_ns() - t0) // 1000
        worker.iops_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_bytes_done += bytes_per_step
        worker.live_ops.num_iops_done += 1
        worker.tpu_transfer_bytes += bytes_per_step
        worker.tpu_transfer_usec += lat_usec
        done += bytes_per_step
