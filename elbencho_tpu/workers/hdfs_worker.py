"""HDFS workload phases via pyarrow's libhdfs binding.

Reference: the HDFS mode of source/workers/LocalWorker.cpp
(hdfsDirModeIterateDirs :7488, hdfsDirModeIterateFiles :7617, wrappers
:2751-2787, init :592-624) using libhdfs (JNI), gated behind HDFS_SUPPORT
(Makefile:142-146). Here the binding is pyarrow.fs.HadoopFileSystem —
gated at runtime with a clear error when libhdfs/JVM are absent, like the
reference's build flag.

Two test hooks, covering complementary layers:

- ``set_filesystem_factory`` replaces the WHOLE filesystem construction
  (tests run phases against pyarrow's LocalFileSystem);
- ``set_hadoop_class`` replaces only the ``pyarrow.fs.HadoopFileSystem``
  class, so the real HadoopFileSystem branch — authority parsing, the
  default host/port, connect-failure wrapping, base-path stripping —
  executes against a HadoopFileSystem-shaped fake (round-2 verdict item
  7: that branch had never run under test).
"""

from __future__ import annotations

import posixpath
import time

from ..phases import BenchPhase, phase_name
from .shared import WorkerException

_fs_factory = None   # test hook: replaces _make_fs entirely
_hadoop_cls = None   # test hook: replaces pyarrow.fs.HadoopFileSystem


def set_filesystem_factory(factory) -> None:
    global _fs_factory
    _fs_factory = factory


def set_hadoop_class(cls) -> None:
    global _hadoop_cls
    _hadoop_cls = cls


def _make_fs(worker):
    if _fs_factory is not None:
        return _fs_factory(worker.cfg)
    hadoop_cls = _hadoop_cls
    if hadoop_cls is None:
        try:
            from pyarrow import fs as pafs
        except ImportError as err:  # pragma: no cover
            raise WorkerException(
                "HDFS support requires pyarrow (not installed)") from err
        hadoop_cls = pafs.HadoopFileSystem
    # paths look like host[:port]/base/dir after the hdfs:// prefix strip
    first = worker.cfg.paths[0]
    authority, _, _base = first.partition("/")
    host, _, port = authority.partition(":")
    try:
        return hadoop_cls(host or "default", int(port) if port else 8020)
    except Exception as err:
        raise WorkerException(
            f"cannot connect to HDFS (libhdfs/JVM required): {err}") from err


def _base_path(worker) -> str:
    first = worker.cfg.paths[0]
    if _fs_factory is not None:
        return first
    _authority, _, base = first.partition("/")
    return "/" + base if base else "/"


def dispatch_hdfs_phase(worker, phase: BenchPhase) -> None:
    if getattr(worker, "_hdfs", None) is None:
        worker._hdfs = _make_fs(worker)
    fs = worker._hdfs
    base = _base_path(worker)
    cfg = worker.cfg
    if phase in (BenchPhase.CREATEDIRS, BenchPhase.DELETEDIRS,
                 BenchPhase.STATDIRS):
        for dir_idx in range(cfg.num_dirs):
            worker.check_interruption_request(force=True)
            path = posixpath.join(base, worker._dir_rel_path(dir_idx))
            t0 = time.perf_counter_ns()
            if phase == BenchPhase.CREATEDIRS:
                fs.create_dir(path, recursive=True)
            elif phase == BenchPhase.DELETEDIRS:
                fs.delete_dir(path)
                # remove the per-rank parent only when it is now empty:
                # pyarrow delete_dir is RECURSIVE (unlike POSIX rmdir), so
                # deleting a non-empty parent would wipe sibling d-dirs
                parent = posixpath.dirname(path)
                if not cfg.do_dir_sharing \
                        and posixpath.basename(parent).startswith("r") \
                        and dir_idx == cfg.num_dirs - 1:
                    try:
                        from pyarrow import fs as pafs
                        leftover = fs.get_file_info(
                            pafs.FileSelector(parent, recursive=False))
                        if not leftover:
                            fs.delete_dir(parent)
                    except OSError:
                        pass
            else:
                fs.get_file_info(path)
            worker.entries_latency_histo.add_latency(
                (time.perf_counter_ns() - t0) // 1000)
            worker.live_ops.num_entries_done += 1
        return
    for dir_idx in range(cfg.num_dirs):
        for file_idx in range(cfg.num_files):
            worker.check_interruption_request(force=True)
            path = posixpath.join(base,
                                  worker._file_rel_path(dir_idx, file_idx))
            t0 = time.perf_counter_ns()
            if phase == BenchPhase.CREATEFILES:
                _write_file(worker, fs, path)
            elif phase == BenchPhase.READFILES:
                _read_file(worker, fs, path)
            elif phase == BenchPhase.STATFILES:
                info = fs.get_file_info(path)
                import pyarrow.fs as pafs
                if info.type == pafs.FileType.NotFound:
                    raise WorkerException(f"stat failed: {path}")
            elif phase == BenchPhase.DELETEFILES:
                try:
                    fs.delete_file(path)
                except (OSError, FileNotFoundError):
                    if not cfg.ignore_delete_errors \
                            and not worker._partial_tolerance(phase):
                        raise
            worker.entries_latency_histo.add_latency(
                (time.perf_counter_ns() - t0) // 1000)
            worker.live_ops.num_entries_done += 1


def _retrying_op(worker, op):
    """--ioretries for one IDEMPOTENT HDFS op (positional reads, stats):
    the transport is a network filesystem by definition, so EIO
    classifies transient (io_errors.py classifier with netfs forced).
    Sequential stream writes are NOT routed through this — see the note
    in _write_file."""
    retrier = getattr(worker, "_io_retrier", None)
    if retrier is None:
        return op()
    return retrier.run(op, netfs=True)


def _write_file(worker, fs, path: str) -> None:
    cfg = worker.cfg
    size, bs = cfg.file_size, cfg.block_size
    with fs.open_output_stream(path) as out:
        offset = 0
        while offset < size:
            worker.check_interruption_request()
            length = min(bs, size - offset)
            buf = worker.rotated_staging_buf()
            worker._pre_write_fill(buf, offset, length)
            t0 = time.perf_counter_ns()
            # NO --ioretries here: the output stream is a sequential
            # append whose position may have advanced before a failure
            # surfaced — re-writing the block would duplicate bytes, not
            # replay them. Only the positional read path retries.
            out.write(bytes(buf[:length]))
            lat = (time.perf_counter_ns() - t0) // 1000
            worker.iops_latency_histo.add_latency(lat)
            if worker._slowops is not None:  # --slowops tail capture
                worker._slowops.record(
                    "hdfs_write", phase_name(worker.shared.current_phase),
                    lat, offset, length, path=path, start_ns=t0)
            worker.live_ops.num_bytes_done += length
            worker.live_ops.num_iops_done += 1
            worker._num_iops_submitted += 1
            offset += length


def _read_file(worker, fs, path: str) -> None:
    cfg = worker.cfg
    size, bs = cfg.file_size, cfg.block_size
    with fs.open_input_file(path) as inp:
        offset = 0
        while offset < size:
            worker.check_interruption_request()
            length = min(bs, size - offset)

            def read_op(length=length, offset=offset):
                from .io_errors import ShortIOError
                data = inp.read_at(length, offset)
                if len(data) != length:
                    # transient for the retrier; the historic message is
                    # restored below when retries are off/exhausted
                    raise ShortIOError(True, offset, len(data), length)
                return data

            t0 = time.perf_counter_ns()
            r0 = worker.io_retries
            try:
                data = _retrying_op(worker, read_op)
            except OSError as err:
                from .io_errors import ShortIOError
                if isinstance(err, ShortIOError):
                    raise WorkerException(
                        f"short HDFS read at {offset} of {path}") from None
                raise
            lat = (time.perf_counter_ns() - t0) // 1000
            if worker._slowops is not None:  # --slowops tail capture
                worker._slowops.record(
                    "hdfs_read", phase_name(worker.shared.current_phase),
                    lat, offset, length, path=path,
                    retries=worker.io_retries - r0, start_ns=t0)
            buf = worker.rotated_staging_buf()
            buf[:length] = data
            worker._post_read_actions(buf, offset, length)
            worker.iops_latency_histo.add_latency(lat)
            worker.live_ops.num_bytes_done += length
            worker.live_ops.num_iops_done += 1
            worker._num_iops_submitted += 1
            offset += length
