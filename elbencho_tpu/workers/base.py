"""Worker base class: per-worker stats container + phase wait loop.

Reference: source/workers/Worker.{h,cpp} — atomic LiveOps (entries/bytes/
iops) x2 (normal + rwmix-read), stonewall snapshots for first-done results
(Worker.h:203), 4 latency histograms (iops/entries x normal/rwmix),
per-phase elapsed time, interruption flag (Worker.h:48-60,167-219).

In CPython the GIL makes single-value counter updates effectively atomic,
so LiveOps are plain ints written by the owning worker thread and read by
the statistics thread; the C++ ioengine writes its counters into a shared
memoryview that the worker syncs from.
"""

from __future__ import annotations

import time

from ..stats.latency_histogram import LatencyHistogram
from .shared import WorkerInterruptedException, WorkersSharedData

INTERRUPT_CHECK_INTERVAL = 128  # ops between interruption checks
                                # (reference: LocalWorker.cpp:70)


class LiveOps:
    """entries/bytes/iops counter triple (reference: LiveOps, Worker.h)."""

    __slots__ = ("num_entries_done", "num_bytes_done", "num_iops_done")

    def __init__(self):
        self.num_entries_done = 0
        self.num_bytes_done = 0
        self.num_iops_done = 0

    def snapshot(self) -> "LiveOps":
        s = LiveOps()
        s.num_entries_done = self.num_entries_done
        s.num_bytes_done = self.num_bytes_done
        s.num_iops_done = self.num_iops_done
        return s

    def add(self, other: "LiveOps") -> None:
        self.num_entries_done += other.num_entries_done
        self.num_bytes_done += other.num_bytes_done
        self.num_iops_done += other.num_iops_done

    def reset(self) -> None:
        self.num_entries_done = 0
        self.num_bytes_done = 0
        self.num_iops_done = 0

    def as_dict(self) -> dict:
        return {"NumEntriesDone": self.num_entries_done,
                "NumBytesDone": self.num_bytes_done,
                "NumIOPSDone": self.num_iops_done}


class Worker:
    def __init__(self, shared: WorkersSharedData, rank: int):
        self.shared = shared
        self.rank = rank
        self.live_ops = LiveOps()
        self.live_ops_rwmix_read = LiveOps()
        self.stonewall_ops = LiveOps()
        self.stonewall_ops_rwmix_read = LiveOps()
        self.stonewall_taken = False
        self.iops_latency_histo = LatencyHistogram()
        self.entries_latency_histo = LatencyHistogram()
        self.iops_latency_histo_rwmix = LatencyHistogram()
        self.entries_latency_histo_rwmix = LatencyHistogram()
        # elapsed usec of finished workers; RemoteWorker appends one entry
        # per remote thread (reference: Worker elapsedUSecVec)
        self.elapsed_usec_vec: "list[int]" = []
        self.stonewall_elapsed_usec = 0
        self.got_phase_work = True
        self.is_interrupted = False
        self.phase_finished = False
        self._ops_since_check = 0
        # --tracefile span recorder; None keeps every instrumentation
        # point a single attribute test (telemetry/tracer.py contract)
        self._tracer = getattr(shared, "tracer", None)
        self.tpu_transfer_bytes = 0   # HBM ingest accounting (TPU data path)
        self.tpu_transfer_usec = 0    # DMA wall time (submit -> ready)
        self.tpu_dispatch_usec = 0    # host-side submit cost (the overhead
                                      # --tpubudget bounds)
        # data-plane fault-tolerance audit (--ioretries/--iotimeout;
        # worker-owned entries of PATH_AUDIT_COUNTERS — see
        # tpu.device.PATH_AUDIT_WORKER_ATTRS)
        self.io_retries = 0       # per-op transient-error retries
        self.io_retry_usec = 0    # total backoff slept for those retries
        self.io_timeouts = 0      # ops cancelled by the --iotimeout deadline
        # unified staging-pool audit (utils/staging_pool.py): local
        # workers serve these from _staging_pool via PATH_AUDIT_POOL_ATTRS;
        # the attributes exist so RemoteWorker ingest and pool-less
        # workers read as zero
        self.pool_buf_reuses = 0
        self.pool_occupancy_hwm = 0
        self.pool_registered_ops = 0
        self.pool_sqpoll_ops = 0
        # pod-slice phase audit (--tpuslice; PATH_AUDIT_WORKER_ATTRS):
        # per-worker shard-ingest MiB plus the driver worker's ICI
        # redistribution counters (workers/tpuslice.py keeps the raw byte
        # totals in _shard_ingest_bytes/_ici_redist_bytes and mirrors the
        # MiB floor here so the wire stays integer-MiB)
        self.shard_ingest_mib = 0
        self.ici_redist_mib = 0
        self.ici_redist_usec = 0
        self.ici_gbps_hwm = 0
        self._shard_ingest_bytes = 0
        self._ici_redist_bytes = 0
        # slow-op forensics audit (--slowops/--opsample;
        # PATH_AUDIT_WORKER_ATTRS): plain ints so RemoteWorker ingest
        # and recorder-less workers read as zero
        self.slow_ops_recorded = 0
        self.op_samples_dropped = 0
        self.tail_p999_usec_hwm = 0
        # --slowops per-worker recorder; None keeps every instrumentation
        # point a single attribute test (telemetry/slowops.py contract)
        from ..telemetry.slowops import make_recorder
        self._slowops = make_recorder(self)

    def oplog(self, op_name: str, entry_name: str = "", offset: int = 0,
              length: int = 0):
        """Per-op trace context (pre+post records incl. error flag);
        no-op without --opslog (reference: OPLOG macros, OpsLogger.h:19-36)."""
        from ..toolkits.ops_logger import null_logged_op
        ops_log = getattr(self, "_ops_log", None)
        if ops_log is None:
            return null_logged_op()
        return ops_log.logged_op(op_name, entry_name, offset, length)

    # -- stats management ---------------------------------------------------

    def reset_stats(self) -> None:
        # per-phase interrupts (e.g. --timelimit expiry) must not leak into
        # the next phase; a user Ctrl-C persists via shared.interrupt_requested
        self.is_interrupted = False
        self.live_ops.reset()
        self.live_ops_rwmix_read.reset()
        self.stonewall_ops.reset()
        self.stonewall_ops_rwmix_read.reset()
        self.stonewall_taken = False
        self.iops_latency_histo.reset()
        self.entries_latency_histo.reset()
        self.iops_latency_histo_rwmix.reset()
        self.entries_latency_histo_rwmix.reset()
        self.elapsed_usec_vec = []
        self.stonewall_elapsed_usec = 0
        self.got_phase_work = True
        self.phase_finished = False
        self._ops_since_check = 0
        self.tpu_transfer_bytes = 0
        self.tpu_transfer_usec = 0
        self.tpu_dispatch_usec = 0
        self.io_retries = 0
        self.io_retry_usec = 0
        self.io_timeouts = 0
        self.pool_buf_reuses = 0
        self.pool_occupancy_hwm = 0
        self.pool_registered_ops = 0
        self.pool_sqpoll_ops = 0
        self.shard_ingest_mib = 0
        self.ici_redist_mib = 0
        self.ici_redist_usec = 0
        self.ici_gbps_hwm = 0
        self._shard_ingest_bytes = 0
        self._ici_redist_bytes = 0
        self.slow_ops_recorded = 0
        self.op_samples_dropped = 0
        self.tail_p999_usec_hwm = 0
        if self._slowops is not None:
            self._slowops.reset_phase()

    def create_stonewall_stats_if_triggered(self) -> None:
        """Snapshot current counters when the first worker finished
        (reference: createStoneWallStats, Worker.h:203)."""
        if self.stonewall_taken or not self.shared.stonewall_triggered:
            return
        self.stonewall_ops = self.live_ops.snapshot()
        self.stonewall_ops_rwmix_read = self.live_ops_rwmix_read.snapshot()
        self.stonewall_elapsed_usec = self.phase_elapsed_usec()
        self.stonewall_taken = True

    def finish_phase_stats(self) -> None:
        """Called by the worker when its phase work is complete."""
        if self._slowops is not None:
            # final TailP999UsecHwm BEFORE anything sums the counters
            # (the service's /benchresult, the master's phase results)
            self._slowops.refresh_hwm()
        if not self.stonewall_taken:
            # first finisher: stonewall stats == final stats
            self.stonewall_ops = self.live_ops.snapshot()
            self.stonewall_ops_rwmix_read = self.live_ops_rwmix_read.snapshot()
            self.stonewall_elapsed_usec = self.phase_elapsed_usec()
            self.stonewall_taken = True
        self.elapsed_usec_vec.append(self.phase_elapsed_usec())
        self.phase_finished = True

    def phase_elapsed_usec(self) -> int:
        return int((time.monotonic()
                    - self.shared.phase_start_monotonic) * 1_000_000)

    # -- interruption -------------------------------------------------------

    def interrupt_execution(self) -> None:
        self.is_interrupted = True

    def check_interruption_request(self, force: bool = False) -> None:
        """Cheap periodic check in hot loops; also the stonewall snapshot
        point (reference: checkInterruptionRequest + stonewall polling).
        Worker-thread only (counter snapshot + _ops_since_check are not
        thread-safe) — helper threads use check_interruption_flag_only."""
        self._ops_since_check += 1
        if not force and self._ops_since_check < INTERRUPT_CHECK_INTERVAL:
            return
        self._ops_since_check = 0
        self.create_stonewall_stats_if_triggered()
        self.check_interruption_flag_only()

    def check_interruption_flag_only(self) -> None:
        """Thread-safe interruption test (no stonewall snapshot, no
        counters) for request threads of the S3 pipeline."""
        if (self.is_interrupted or self.shared.interrupt_requested
                or self.shared.phase_time_expired):
            raise WorkerInterruptedException("worker interruption requested")

    # -- thread entry -------------------------------------------------------

    def run(self) -> None:
        raise NotImplementedError

    def thread_start(self) -> None:
        try:
            self.run()
        except Exception as err:  # noqa: BLE001 - worker errors are reported
            from ..toolkits import logger
            logger.log_error(f"Worker {self.rank} terminated on error: "
                             f"{type(err).__name__}: {err}")
            self.shared.inc_num_workers_done_with_error(err)
