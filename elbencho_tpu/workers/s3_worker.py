"""S3/object-storage workload phases for LocalWorker.

Reference: the S3 surface of source/workers/LocalWorker.cpp —
s3ModeIterateBuckets :3822, s3ModeIterateObjects :3920-4059, upload single
:4810 / multipart :4905, download :6137, stat :6489, delete :6516, listing
:6549 (single) / :6641 (parallel) / verify :6797, multi-delete :6850,
object/bucket ACL :4623-4742/:6985-7107, tagging :4495-4589/:7109-7204.

Object namespace matches dir mode: "<prefix>r<rank>/d<dir>/r<rank>-f<file>"
so WRITE/READ/STAT/RMFILES phases line up across POSIX and S3 front-ends.
The TPU HBM staging seam is identical: downloaded blocks go through
worker._tpu.host_to_device, uploads originate from the same io buffer fill
path (on-device pool with --tpuids).
"""

from __future__ import annotations

import time

from ..phases import BenchPhase, phase_name
from ..toolkits.s3_upload_store import shared_upload_store
from .shared import WorkerException

MAX_LIST_PAGE = 1000


def _retry_notify_for(worker):
    """Per-retry hook feeding the worker's IoRetries/IoRetryUsec audit
    counters (--ioretries unification: object-transport retries count in
    the same columns as POSIX per-op retries). With --s3single the shared
    client attributes retries to its creating worker. Locked: unlike the
    worker-thread-owned live counters, this hook fires from the S3
    pipeline's executor threads, where a bare += would lose updates."""
    import threading
    lock = threading.Lock()

    def notify(slept_secs: float) -> None:
        with lock:
            worker.io_retries += 1
            worker.io_retry_usec += int(slept_secs * 1_000_000)
    return notify


def _client(worker):
    if getattr(worker, "_s3_client", None) is None:
        from ..toolkits.s3_tk import make_client_for_rank
        if getattr(worker.cfg, "use_s3_client_singleton", False):
            # --s3single: ONE client object for every worker of this
            # process (reference: S3 client singleton, ProgArgs.h:368).
            # Safe because connections inside the client are per thread;
            # interruption checks use the thread-safe shared-flag test.
            # Worker teardown must NOT close a shared client (see
            # LocalWorker._close_s3_client).
            shared = worker.shared
            with shared.cond:
                client = getattr(shared, "s3_client_singleton", None)
                if client is None:
                    client = make_client_for_rank(
                        worker.cfg, 0,
                        interrupt_check=worker.check_interruption_flag_only,
                        retry_notify=_retry_notify_for(worker))
                    shared.s3_client_singleton = client
            worker._s3_client = client
        else:
            worker._s3_client = make_client_for_rank(
                worker.cfg, worker.rank,
                interrupt_check=lambda: worker.check_interruption_request(
                    force=True),
                retry_notify=_retry_notify_for(worker))
    return worker._s3_client


# ---------------------------------------------------------------------------
# async request pipeline (reference: the async S3 phase variants keep up to
# --iodepth requests in flight via promise/future contexts,
# LocalWorker.cpp:109-161 + MPU-async :5155 / download-async :6280)
# ---------------------------------------------------------------------------

class _S3Pipeline:
    """Up to --iodepth S3 requests in flight per worker. Submission and
    counter updates stay on the worker thread (seed-then-refill like the
    AIO loop); executor threads only run the HTTP round-trips, each on its
    own S3Client connection."""

    def __init__(self, worker, depth: int):
        import concurrent.futures
        import threading
        self.worker = worker
        self.depth = max(depth, 1)
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=self.depth,
            thread_name_prefix=f"s3pipe-r{worker.rank}")
        self._tls = threading.local()
        self._clients: "list" = []
        self._clients_lock = threading.Lock()
        self._inflight: "list" = []
        self._warm_clients()

    def _warm_clients(self) -> None:
        """Construct every executor thread's S3 client up front (a
        barrier pins one task per thread), so per-op latencies never
        include client construction: measured spans are pure
        submission->completion like the reference's promise/future
        variants (LocalWorker.cpp:5155, 6280)."""
        import threading
        barrier = threading.Barrier(self.depth)

        def warm():
            try:
                self._thread_client()
            except BaseException:
                # release siblings immediately: without the abort they sit
                # at barrier.wait for the full timeout before the
                # construction error can surface via fut.result()
                barrier.abort()
                raise
            barrier.wait(timeout=60)

        futs = [self._pool.submit(warm) for _ in range(self.depth)]
        errors = []
        for fut in futs:
            try:
                fut.result()  # construction errors surface at prepare time
            except threading.BrokenBarrierError as err:
                errors.append(err)  # sibling released by abort(), not root cause
            except Exception as err:  # noqa: BLE001
                errors.insert(0, err)  # real construction error first
        if errors:
            raise errors[0]

    def _thread_client(self):
        client = getattr(self._tls, "client", None)
        if client is None:
            if getattr(self.worker.cfg, "use_s3_client_singleton", False):
                # --s3single governs the async pipeline too: every
                # executor thread uses the process-wide client (safe:
                # connections inside it are per thread). Not added to
                # self._clients — pipeline teardown must not close it.
                self._tls.client = _client(self.worker)
                return self._tls.client
            from ..toolkits.s3_tk import make_client_for_rank
            # rank-based endpoint/credential selection stays per WORKER so
            # round-robin semantics don't depend on executor thread count;
            # flag-only interrupt check: stonewall snapshots are worker-
            # thread business
            client = make_client_for_rank(
                self.worker.cfg, self.worker.rank,
                interrupt_check=self.worker.check_interruption_flag_only,
                retry_notify=_retry_notify_for(self.worker))
            self._tls.client = client
            with self._clients_lock:
                self._clients.append(client)
        return client

    def submit(self, fn, *args, **kwargs):
        """fn(client, *args) -> bytes_done; returns once a slot is free.
        Completed requests are harvested (counters updated) here and at
        drain(). Latency is timed from THIS submission call to request
        completion — reference semantics (LocalWorker.cpp:5155): queue
        wait inside a saturated executor counts, the measurement is not
        just the HTTP service time."""
        while len(self._inflight) >= self.depth:
            self._harvest()
        t_submit = time.perf_counter_ns()

        def task():
            client = self._thread_client()
            nbytes = fn(client, *args, **kwargs)
            return nbytes, (time.perf_counter_ns() - t_submit) // 1000

        self._inflight.append(self._pool.submit(task))

    def _harvest(self) -> None:
        import concurrent.futures
        done, pending = concurrent.futures.wait(
            self._inflight,
            return_when=concurrent.futures.FIRST_COMPLETED)
        self._inflight = list(pending)
        worker = self.worker
        for fut in done:
            nbytes, lat_usec = fut.result()  # re-raises request errors
            worker.iops_latency_histo.add_latency(lat_usec)
            worker.live_ops.num_bytes_done += nbytes
            worker.live_ops.num_iops_done += 1
            worker._num_iops_submitted += 1
            tracer = getattr(worker, "_tracer", None)
            if tracer is not None:  # --tracefile op span
                tracer.record_op(
                    "s3_req", phase_name(worker.shared.current_phase),
                    tracer.now_ns() - lat_usec * 1000, lat_usec,
                    worker.rank, 0, nbytes)
            slowops_rec = getattr(worker, "_slowops", None)
            if slowops_rec is not None:  # --slowops tail capture
                slowops_rec.record(
                    "s3_req", phase_name(worker.shared.current_phase),
                    lat_usec, 0, nbytes,
                    start_ns=time.perf_counter_ns() - lat_usec * 1000)

    def drain(self) -> None:
        while self._inflight:
            self._harvest()

    def abort(self) -> None:
        """Interrupt/error path: wait out in-flight requests (their
        clients poll the worker interrupt flag) without raising."""
        for fut in self._inflight:
            try:
                fut.result()
            except Exception:  # noqa: BLE001 - phase is aborting anyway
                pass
        self._inflight = []

    def close(self) -> None:
        self._pool.shutdown(wait=True)
        with self._clients_lock:
            for client in self._clients:
                client.close()
            self._clients = []


def _pipeline(worker) -> "_S3Pipeline | None":
    """Per-phase pipeline when --iodepth > 1 (reference async variants)."""
    if worker.cfg.io_depth <= 1:
        return None
    pipe = getattr(worker, "_s3_pipeline", None)
    if pipe is None:
        pipe = _S3Pipeline(worker, worker.cfg.io_depth)
        worker._s3_pipeline = pipe
    return pipe


def dispatch_s3_phase(worker, phase: BenchPhase) -> None:
    cfg = worker.cfg
    handlers = {
        BenchPhase.CREATEDIRS: _iterate_buckets,
        BenchPhase.DELETEDIRS: _iterate_buckets,
        BenchPhase.STATDIRS: _iterate_buckets,
        BenchPhase.CREATEFILES: _iterate_objects,
        BenchPhase.READFILES: _iterate_objects,
        BenchPhase.STATFILES: _iterate_objects,
        BenchPhase.DELETEFILES: _iterate_objects,
        BenchPhase.LISTOBJECTS: _list_objects_single,
        BenchPhase.LISTOBJPARALLEL: _list_objects_parallel,
        BenchPhase.MULTIDELOBJ: _multi_delete,
        BenchPhase.PUTOBJACL: _obj_acl,
        BenchPhase.GETOBJACL: _obj_acl,
        BenchPhase.PUTBUCKETACL: _bucket_acl,
        BenchPhase.GETBUCKETACL: _bucket_acl,
        BenchPhase.PUT_OBJ_MD: _obj_tagging,
        BenchPhase.GET_OBJ_MD: _obj_tagging,
        BenchPhase.DEL_OBJ_MD: _obj_tagging,
        BenchPhase.PUT_BUCKET_MD: _bucket_metadata,
        BenchPhase.GET_BUCKET_MD: _bucket_metadata,
        BenchPhase.DEL_BUCKET_MD: _bucket_metadata,
        BenchPhase.S3MPUCOMPLETE: _mpu_complete_phase,
    }
    handler = handlers.get(phase)
    if handler is None:
        raise WorkerException(
            f"S3 phase {phase.name} is not implemented yet")
    handler(worker, phase)
    if worker._tpu is not None:
        # drain pipelined staging + --tpubudget checks (guarded for
        # --tpufallback chip failover like the POSIX loops)
        worker._tpu_guarded(worker._tpu.flush)
        worker._sync_tpu_usec()


# ---------------------------------------------------------------------------
# namespace helpers (same formulas as POSIX dir mode)
# ---------------------------------------------------------------------------

def _object_key(worker, dir_idx: int, file_idx: int) -> str:
    cfg = worker.cfg
    if cfg.s3_mpu_sharing:
        # shared object namespace: every worker uploads parts of the SAME
        # objects (reference: --s3mpusharing semantics)
        return f"{cfg.s3_object_prefix}d{dir_idx}-f{file_idx}"
    return (f"{cfg.s3_object_prefix}"
            f"{worker._file_rel_path(dir_idx, file_idx)}")


def _bucket_for_dir(worker, dir_idx: int) -> str:
    return worker._bench_path_for_dir(dir_idx)


def _iter_entries(worker):
    for dir_idx in range(worker.cfg.num_dirs):
        for file_idx in range(worker.cfg.num_files):
            yield (_bucket_for_dir(worker, dir_idx),
                   _object_key(worker, dir_idx, file_idx))


# ---------------------------------------------------------------------------
# buckets (reference: s3ModeIterateBuckets :3822)
# ---------------------------------------------------------------------------

def _iterate_buckets(worker, phase: BenchPhase) -> None:
    cfg = worker.cfg
    client = _client(worker)
    ndst = max(1, cfg.num_dataset_threads)
    got_work = False
    for idx, bucket in enumerate(cfg.paths):
        if idx % ndst != worker.rank % ndst:
            continue
        got_work = True
        worker.check_interruption_request(force=True)
        with worker.oplog(phase.name.lower(), bucket):
            t0 = time.perf_counter_ns()
            if phase == BenchPhase.CREATEDIRS:
                client.create_bucket(bucket)
            elif phase == BenchPhase.DELETEDIRS:
                client.delete_bucket(bucket)
            else:  # STATDIRS
                if not client.head_bucket(bucket):
                    raise WorkerException(f"bucket not found: {bucket}")
            lat_usec = (time.perf_counter_ns() - t0) // 1000
        worker.entries_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_entries_done += 1
    worker.got_phase_work = got_work


# ---------------------------------------------------------------------------
# objects (reference: s3ModeIterateObjects :3920-4059)
# ---------------------------------------------------------------------------

def _ignoring_errors_call(worker, fn) -> bool:
    """--s3ignoreerrors stress mode: keep going on request failures
    (retries happen inside S3Client.request)."""
    try:
        fn()
        return True
    except Exception:
        if worker.cfg.s3_ignore_errors:
            return False
        raise


def _iterate_objects(worker, phase: BenchPhase) -> None:
    cfg = worker.cfg
    if phase == BenchPhase.READFILES and cfg.s3_rand_obj_select:
        _download_random_objects(worker)
        return
    for bucket, key in _iter_entries(worker):
        worker.check_interruption_request(force=True)
        with worker.oplog(phase.name.lower(), f"{bucket}/{key}") as op_rec:
            t0 = time.perf_counter_ns()
            if phase == BenchPhase.CREATEFILES:
                op_rec.error = not _ignoring_errors_call(
                    worker, lambda: _upload_object(worker, bucket, key))
            elif phase == BenchPhase.READFILES:
                op_rec.error = not _ignoring_errors_call(
                    worker, lambda: _download_object(worker, bucket, key))
            elif phase == BenchPhase.STATFILES:
                op_rec.error = not _ignoring_errors_call(
                    worker, lambda: _client(worker).head_object(
                        bucket, key,
                        extra_headers=_sse_c_headers(cfg) or None))
            elif phase == BenchPhase.DELETEFILES:
                try:
                    _client(worker).delete_object(bucket, key)
                except Exception:
                    if not cfg.ignore_delete_errors \
                            and not cfg.s3_ignore_errors \
                            and not worker._partial_tolerance(phase):
                        raise
                    op_rec.error = True
            lat_usec = (time.perf_counter_ns() - t0) // 1000
        worker.entries_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_entries_done += 1


def _download_random_objects(worker) -> None:
    """--s3randobj: random aligned offsets of random objects until this
    worker's share of --randamount is read (reference: s3 rand :4069)."""
    cfg = worker.cfg
    client = _client(worker)
    size, bs = cfg.file_size, cfg.block_size
    ndst = max(1, cfg.num_dataset_threads)
    amount = (cfg.random_amount or size * cfg.num_dirs * cfg.num_files) \
        // ndst
    rand = worker._rand_offset_algo
    blocks_per_obj = max(1, size // bs)
    done = 0
    from .local_worker import LocalWorker
    while done < amount:
        worker.check_interruption_request()
        rank_r = rand.next64() % ndst
        dir_r = rand.next64() % cfg.num_dirs
        file_r = rand.next64() % cfg.num_files
        if cfg.s3_mpu_sharing:
            key = f"{cfg.s3_object_prefix}d{dir_r}-f{file_r}"
        else:
            key = cfg.s3_object_prefix + LocalWorker.file_rel_path_for(
                rank_r, dir_r, file_r, cfg.do_dir_sharing)
        bucket = cfg.paths[(rank_r + dir_r) % len(cfg.paths)]
        offset = (rand.next64() % blocks_per_obj) * bs
        length = min(bs, size - offset, amount - done)
        if length <= 0:
            break
        if worker._rate_limiter_read:
            worker._rate_limiter_read.wait(length)
        t0 = time.perf_counter_ns()
        data = client.get_object(bucket, key, range_start=offset,
                                 range_len=length,
                                 extra_headers=_sse_c_headers(cfg) or None)
        lat = (time.perf_counter_ns() - t0) // 1000
        if len(data) != length:
            raise WorkerException(
                f"short random S3 read for {bucket}/{key} at {offset}")
        buf = worker.rotated_staging_buf()
        buf[:length] = data
        worker._post_read_actions(buf, offset, length)
        worker.iops_latency_histo.add_latency(lat)
        worker.live_ops.num_bytes_done += length
        worker.live_ops.num_iops_done += 1
        worker._num_iops_submitted += 1
        done += length
    worker.live_ops.num_entries_done += 1


def _upload_object(worker, bucket: str, key: str) -> None:
    """Single PUT for small objects / --s3single; multipart otherwise
    (reference: upload single :4810, MPU :4905; shared MPU :5455 via
    the S3UploadStore when --s3mpusharing)."""
    cfg = worker.cfg
    client = _client(worker)
    size, bs = cfg.file_size, cfg.block_size
    limiter = worker._rate_limiter_write
    if cfg.s3_mpu_sharing and size > bs:
        _upload_object_shared_mpu(worker, bucket, key)
        return
    algo = cfg.s3_checksum_algo.lower()
    if size <= bs or cfg.s3_no_mpu:
        if limiter:
            limiter.wait(size)
        # assemble the full payload block-by-block: io buffers are only
        # block_size bytes, and the fill path works per block
        body = b"".join(
            _next_upload_block(worker, off, min(bs, size - off))
            for off in range(0, size, bs)) if size else b""
        # checksum before t0: client-side hashing must not count as
        # request latency
        headers = _body_headers(cfg, body, _upload_init_headers(cfg))
        t0 = time.perf_counter_ns()
        client.put_object(bucket, key, body, extra_headers=headers)
        lat_usec = (time.perf_counter_ns() - t0) // 1000
        worker.iops_latency_histo.add_latency(lat_usec)
        worker.live_ops.num_bytes_done += size
        worker.live_ops.num_iops_done += 1
        worker._num_iops_submitted += 1
        if worker._tracer is not None:  # --tracefile op span
            worker._tracer.record_op(
                "s3_put", phase_name(worker.shared.current_phase), t0,
                lat_usec, worker.rank, 0, size)
        if worker._slowops is not None:  # --slowops tail capture
            worker._slowops.record(
                "s3_put", phase_name(worker.shared.current_phase),
                lat_usec, 0, size, path=f"{bucket}/{key}", start_ns=t0)
        return
    upload_id = client.create_multipart_upload(
        bucket, key, extra_headers=_mpu_init_headers(cfg))
    parts: "list[tuple]" = []
    # async variant: up to --iodepth part uploads in flight (reference:
    # s3ModeUploadObjectMultiPartAsync, LocalWorker.cpp:5155)
    pipe = _pipeline(worker)

    def upload_one(part_client, part_number, body, headers):
        etag = part_client.upload_part(bucket, key, upload_id, part_number,
                                       body, extra_headers=headers)
        if algo:  # completion XML must carry each part's checksum
            parts.append((part_number, etag,
                          headers[f"x-amz-checksum-{algo}"]))
        else:
            parts.append((part_number, etag))
        return len(body)

    try:
        offset = 0
        part_number = 1
        num_parts = (size + bs - 1) // bs
        while offset < size:
            worker.check_interruption_request()
            if part_number < num_parts:
                length = min(bs, size - offset)
                if cfg.s3_mpu_size_variance:
                    # --s3mpusizevar: random shrink per non-final part;
                    # the LAST part absorbs the difference (reference:
                    # s3MpuSizeVariance, part count stays size/blocksize)
                    shrink = worker._rand_offset_algo.next64() \
                        % (min(cfg.s3_mpu_size_variance, length - 1) + 1)
                    length -= shrink
            else:
                length = size - offset  # final part absorbs all shrinkage
            if limiter:
                limiter.wait(length)
            if length <= bs:
                body = _next_upload_block(worker, offset, length)
            else:  # enlarged final part spans multiple fill blocks
                body = b"".join(
                    _next_upload_block(worker, offset + sub,
                                       min(bs, length - sub))
                    for sub in range(0, length, bs))
            headers = _body_headers(cfg, body, _sse_c_headers(cfg) or None)
            if pipe is not None:
                pipe.submit(upload_one, part_number, body, headers)
            else:
                t0 = time.perf_counter_ns()
                upload_one(client, part_number, body, headers)
                worker.iops_latency_histo.add_latency(
                    (time.perf_counter_ns() - t0) // 1000)
                worker.live_ops.num_bytes_done += length
                worker.live_ops.num_iops_done += 1
                worker._num_iops_submitted += 1
            offset += length
            part_number += 1
        if pipe is not None:
            pipe.drain()  # all parts must finish before completion
        if cfg.s3_no_mpu_completion:
            return  # --s3nompucompl: leave the upload incomplete on purpose
        _complete_mpu_ignoring_404(worker, client, bucket, key, upload_id,
                                   parts)
    except BaseException:
        # abort on interrupt/error so no orphaned MPU is left behind
        # (reference: LocalWorker.cpp:6044-6135)
        if pipe is not None:
            pipe.abort()
        try:
            client.abort_multipart_upload(bucket, key, upload_id)
        except Exception:  # noqa: BLE001
            pass
        raise


def _complete_mpu_ignoring_404(worker, client, bucket, key, upload_id,
                               parts) -> None:
    """CompleteMultipartUpload; --s3multiignore404 tolerates a 404 from a
    completion that already succeeded via a retried request."""
    from ..toolkits.s3_tk import S3Error
    try:
        client.complete_multipart_upload(
            bucket, key, upload_id, parts,
            checksum_algo=worker.cfg.s3_checksum_algo)
    except S3Error as err:
        if not (err.status == 404
                and worker.cfg.s3_ignore_mpu_completion_404):
            raise


def _upload_object_shared_mpu(worker, bucket: str, key: str) -> None:
    """Shared multipart upload: workers upload interleaved parts of one
    object through the process-wide S3UploadStore; whichever worker
    completes the final byte sends CompleteMultipartUpload (reference:
    s3ModeUploadObjectMultiPartShared :5455 + S3UploadStore.h:73-105)."""
    cfg = worker.cfg
    client = _client(worker)
    size, bs = cfg.file_size, cfg.block_size
    ndst = max(1, cfg.num_dataset_threads)
    rank = worker.rank % ndst
    num_parts = (size + bs - 1) // bs
    upload_id = shared_upload_store.get_or_create_upload_id(
        bucket, key, size,
        lambda: client.create_multipart_upload(
            bucket, key, extra_headers=_sse_headers(cfg)))
    got_final = False
    try:
        for part_idx in range(rank, num_parts, ndst):
            worker.check_interruption_request()
            offset = part_idx * bs
            length = min(bs, size - offset)
            if worker._rate_limiter_write:
                worker._rate_limiter_write.wait(length)
            body = _next_upload_block(worker, offset, length)
            t0 = time.perf_counter_ns()
            etag = client.upload_part(bucket, key, upload_id,
                                      part_idx + 1, body,
                                      extra_headers=_sse_c_headers(cfg)
                                      or None)
            worker.iops_latency_histo.add_latency(
                (time.perf_counter_ns() - t0) // 1000)
            worker.live_ops.num_bytes_done += length
            worker.live_ops.num_iops_done += 1
            worker._num_iops_submitted += 1
            got_final = shared_upload_store.add_completed_part(
                bucket, key, part_idx + 1, etag, length)
        if got_final and not cfg.run_s3_mpu_complete_phase \
                and not cfg.s3_no_mpu_completion:
            # inline completion; with --s3mpucomplphase the separate
            # MPUCOMPL phase sends the completions instead
            _complete_mpu_ignoring_404(
                worker, client, bucket, key, upload_id,
                shared_upload_store.get_completed_parts(bucket, key))
    except BaseException:
        upload_id = shared_upload_store.mark_aborted(bucket, key)
        if upload_id:
            try:
                client.abort_multipart_upload(bucket, key, upload_id)
            except Exception:  # noqa: BLE001
                pass
        raise


def _next_upload_block(worker, offset: int, length: int) -> bytes:
    """Upload payload from the worker's io buffer, via the same pre-write
    fill path as POSIX mode (verify pattern / block variance / TPU pool)."""
    buf = worker.rotated_staging_buf()
    worker._pre_write_fill(buf, offset, length)
    return bytes(buf[:length])


def _get_block(client, cfg, bucket: str, key: str, whole_object: bool,
               offset: int, length: int, sse_c) -> "tuple[int, bytes]":
    """One download block: whole-object or ranged GET, optionally
    stream-and-discard (--s3fastget). Returns (bytes_got, data) — data is
    b'' in discard mode. Raises on short reads."""
    rng = (None, None) if whole_object else (offset, length)
    if cfg.s3_fast_get:
        got, data = client.get_object_discard(
            bucket, key, range_start=rng[0], range_len=rng[1],
            extra_headers=sse_c), b""
    else:
        data = client.get_object(bucket, key, range_start=rng[0],
                                 range_len=rng[1], extra_headers=sse_c)
        got = len(data)
    if got != length:
        raise WorkerException(
            f"short S3 read for {bucket}/{key} at {offset}: "
            f"{got} != {length}")
    return got, data


def _download_object(worker, bucket: str, key: str) -> None:
    """Whole-object GET when blocksize >= filesize, ranged GETs per block
    otherwise (reference: download :6137). With --iodepth > 1 and no
    buffer post-processing (no --verify / --tpuids), up to iodepth ranged
    GETs run in flight (reference: async download :6280)."""
    cfg = worker.cfg
    client = _client(worker)
    size, bs = cfg.file_size, cfg.block_size
    whole = size <= bs
    limiter = worker._rate_limiter_read
    sse_c = _sse_c_headers(cfg) or None
    pipe = _pipeline(worker) if (worker._tpu is None
                                 and not cfg.integrity_check_salt) else None
    if pipe is not None:
        def get_one(get_client, offset, length):
            return _get_block(get_client, cfg, bucket, key, whole, offset,
                              length, sse_c)[0]

        try:
            offset = 0
            while offset < size:
                worker.check_interruption_request()
                length = min(bs, size - offset)
                if limiter:
                    limiter.wait(length)
                pipe.submit(get_one, offset, length)
                offset += length
            pipe.drain()  # entry completes when every block arrived
        except BaseException:
            pipe.abort()
            raise
        return
    offset = 0
    while offset < size:
        worker.check_interruption_request()
        length = min(bs, size - offset)
        if limiter:
            limiter.wait(length)
        t0 = time.perf_counter_ns()
        got, data = _get_block(client, cfg, bucket, key, whole, offset,
                               length, sse_c)
        lat_usec = (time.perf_counter_ns() - t0) // 1000
        worker.iops_latency_histo.add_latency(lat_usec)
        if worker._tracer is not None:  # --tracefile op span
            worker._tracer.record_op(
                "s3_get", phase_name(worker.shared.current_phase), t0,
                lat_usec, worker.rank, offset, length)
        if worker._slowops is not None:  # --slowops tail capture
            worker._slowops.record(
                "s3_get", phase_name(worker.shared.current_phase),
                lat_usec, offset, length, path=f"{bucket}/{key}",
                start_ns=t0)
        if not cfg.s3_fast_get:
            buf = worker.rotated_staging_buf()
            buf[:length] = data
            worker._post_read_actions(buf, offset, length)
        worker.live_ops.num_bytes_done += got
        worker.live_ops.num_iops_done += 1
        worker._num_iops_submitted += 1
        offset += length


# ---------------------------------------------------------------------------
# listing (reference: :6549 single / :6641 parallel / verify :6797)
# ---------------------------------------------------------------------------

def _expected_keys(worker) -> "set[str]":
    """Every key any rank would have written, built from the same namespace
    helper the writers use (so --dirsharing etc. can't diverge)."""
    from .local_worker import LocalWorker
    cfg = worker.cfg
    out = set()
    for rank in range(max(cfg.num_dataset_threads, cfg.num_threads)):
        for dir_idx in range(cfg.num_dirs):
            for file_idx in range(cfg.num_files):
                if cfg.s3_mpu_sharing:
                    out.add(f"{cfg.s3_object_prefix}d{dir_idx}-f{file_idx}")
                else:
                    out.add(cfg.s3_object_prefix
                            + LocalWorker.file_rel_path_for(
                                rank, dir_idx, file_idx,
                                cfg.do_dir_sharing))
    return out


def _list_bucket(worker, bucket: str, prefix: str, limit: int) -> int:
    client = _client(worker)
    token = ""
    total = 0
    # hoisted: the expected set is O(dataset) to build, not per page
    expected = _expected_keys(worker) \
        if worker.cfg.do_list_objects_verify else None
    while total < limit:
        worker.check_interruption_request(force=True)
        t0 = time.perf_counter_ns()
        keys, token = client.list_objects(
            bucket, prefix=prefix,
            max_keys=min(MAX_LIST_PAGE, limit - total),
            continuation_token=token)
        worker.iops_latency_histo.add_latency(
            (time.perf_counter_ns() - t0) // 1000)
        total += len(keys)
        worker.live_ops.num_entries_done += len(keys)
        worker.live_ops.num_iops_done += 1
        if expected is not None:
            unexpected = [k for k in keys if k not in expected]
            if unexpected:
                raise WorkerException(
                    f"listing verification failed: unexpected keys "
                    f"{unexpected[:3]}...")
        if not token:
            break
    return total


def _list_objects_single(worker, phase: BenchPhase) -> None:
    """Only the first worker lists (reference: :6549)."""
    cfg = worker.cfg
    if worker.rank % max(1, cfg.num_threads) != 0:
        worker.got_phase_work = False
        return
    limit = cfg.run_list_objects_num or (1 << 62)
    for bucket in cfg.paths:
        _list_bucket(worker, bucket, cfg.s3_object_prefix, limit)


def _list_objects_parallel(worker, phase: BenchPhase) -> None:
    """Each worker lists its own rank prefix (reference: :6641). With
    --dirsharing keys are not rank-prefixed, so every worker lists the
    full shared prefix instead."""
    cfg = worker.cfg
    limit = cfg.run_list_objects_num or (1 << 62)
    if cfg.do_dir_sharing or cfg.s3_mpu_sharing:
        prefix = cfg.s3_object_prefix
    else:
        prefix = f"{cfg.s3_object_prefix}r{worker.rank}/"
    for bucket in cfg.paths:
        _list_bucket(worker, bucket, prefix, limit)


def _multi_delete(worker, phase: BenchPhase) -> None:
    """Batched DeleteObjects of this worker's own objects
    (reference: :6850)."""
    cfg = worker.cfg
    client = _client(worker)
    batch_size = max(1, cfg.run_multi_delete_num)
    batch: "list[str]" = []
    by_bucket: "dict[str, list[str]]" = {}
    for bucket, key in _iter_entries(worker):
        by_bucket.setdefault(bucket, []).append(key)
    for bucket, keys in by_bucket.items():
        for i in range(0, len(keys), batch_size):
            worker.check_interruption_request(force=True)
            batch = keys[i:i + batch_size]
            t0 = time.perf_counter_ns()
            client.delete_objects(bucket, batch)
            worker.iops_latency_histo.add_latency(
                (time.perf_counter_ns() - t0) // 1000)
            worker.live_ops.num_entries_done += len(batch)
            worker.live_ops.num_iops_done += 1


def _mpu_complete_phase(worker, phase: BenchPhase) -> None:
    """MPUCOMPL: complete all shared multipart uploads recorded by the
    preceding WRITE phase (reference: separate MPUCOMPLETE phase for
    --s3mpusharing, Coordinator phase table + MPU complete :5936)."""
    cfg = worker.cfg
    if worker.rank % max(1, cfg.num_threads) != 0:
        worker.got_phase_work = False
        return
    client = _client(worker)
    completed = shared_upload_store.pop_all_complete()
    for bucket, key, upload_id, parts in completed:
        worker.check_interruption_request(force=True)
        t0 = time.perf_counter_ns()
        client.complete_multipart_upload(bucket, key, upload_id, parts)
        worker.entries_latency_histo.add_latency(
            (time.perf_counter_ns() - t0) // 1000)
        worker.live_ops.num_entries_done += 1


# ---------------------------------------------------------------------------
# ACL / tagging metadata phases
# ---------------------------------------------------------------------------

def _acl_headers(cfg) -> "dict":
    """Grant headers from --s3aclgrantee/--s3aclgtype/--s3aclgrants."""
    from ..toolkits.s3_tk import build_acl_headers
    try:
        return build_acl_headers(cfg.s3_acl_grantee,
                                 cfg.s3_acl_grantee_type, cfg.s3_acl_grants)
    except ValueError as err:
        raise WorkerException(str(err)) from err


#: canned ACL -> grantee marker that must appear in the ACL document,
#: per object backend (S3 XML group URIs vs GCS JSON ACL entities)
_CANNED_ACL_MARKERS = {
    "s3": {
        "public-read": b"groups/global/AllUsers",
        "public-read-write": b"groups/global/AllUsers",
        "authenticated-read": b"groups/global/AuthenticatedUsers",
    },
    "gcs": {
        "public-read": b"allUsers",
        "public-read-write": b"allUsers",
        "authenticated-read": b"allAuthenticatedUsers",
    },
}


def _verify_acl(cfg, acl_xml: bytes, what: str) -> None:
    """--s3aclverify: the configured grantee (or the canned ACL's group
    URI / predefined-ACL name) must appear in the returned ACL document
    (reference: doS3AclVerify in the get-ACL phases)."""
    if not cfg.do_s3_acl_verify or not cfg.s3_acl_grantee:
        return
    grantee = cfg.s3_acl_grantee
    if grantee == "private":
        return  # owner-only ACL: nothing beyond the owner grant to check
    backend = getattr(cfg, "object_backend", "") or "s3"
    markers = _CANNED_ACL_MARKERS.get(backend, _CANNED_ACL_MARKERS["s3"])
    marker = markers.get(grantee) \
        or (grantee.partition("=")[2] or grantee).encode()
    if marker not in acl_xml:
        raise WorkerException(
            f"ACL verification failed: {marker!r} not in {what} ACL reply")


def _obj_acl(worker, phase: BenchPhase) -> None:
    cfg = worker.cfg
    client = _client(worker)
    put = phase == BenchPhase.PUTOBJACL
    acl_headers = _acl_headers(cfg) if put else None  # constant: hoisted
    for bucket, key in _iter_entries(worker):
        worker.check_interruption_request(force=True)
        t0 = time.perf_counter_ns()
        if put:
            client.put_object_acl(bucket, key, acl_headers=acl_headers)
        else:
            acl_xml = client.get_object_acl(bucket, key)
            _verify_acl(cfg, acl_xml, f"object {key}")
        worker.entries_latency_histo.add_latency(
            (time.perf_counter_ns() - t0) // 1000)
        worker.live_ops.num_entries_done += 1


def _bucket_acl(worker, phase: BenchPhase) -> None:
    cfg = worker.cfg
    client = _client(worker)
    put = phase == BenchPhase.PUTBUCKETACL
    acl_headers = _acl_headers(cfg) if put else None  # constant: hoisted
    ndst = max(1, cfg.num_dataset_threads)
    got_work = False
    for idx, bucket in enumerate(cfg.paths):
        if idx % ndst != worker.rank % ndst:
            continue
        got_work = True
        t0 = time.perf_counter_ns()
        if put:
            client.put_bucket_acl(bucket, acl_headers=acl_headers)
        else:
            acl_xml = client.get_bucket_acl(bucket)
            _verify_acl(cfg, acl_xml, f"bucket {bucket}")
        worker.entries_latency_histo.add_latency(
            (time.perf_counter_ns() - t0) // 1000)
        worker.live_ops.num_entries_done += 1
    worker.got_phase_work = got_work


_BENCH_TAGS = {"elbencho-tpu": "bench"}


_sse_c_cache: "dict[str, dict]" = {}


def _sse_c_headers(cfg) -> "dict":
    """SSE-C customer-key headers — required on BOTH upload and every
    retrieval of an SSE-C object (GET/HEAD). Computed once per key (the
    MD5/base64 round-trip must not tax the measured hot path)."""
    key = cfg.s3_sse_customer_key
    if not key:
        return {}
    cached = _sse_c_cache.get(key)
    if cached is None:
        import base64
        import hashlib
        raw = base64.b64decode(key)
        cached = {
            "x-amz-server-side-encryption-customer-algorithm": "AES256",
            "x-amz-server-side-encryption-customer-key": key,
            "x-amz-server-side-encryption-customer-key-MD5":
                base64.b64encode(hashlib.md5(raw).digest()).decode(),
        }
        _sse_c_cache[key] = cached
    return cached


def _sse_headers(cfg) -> "dict | None":
    """Full server-side encryption headers for single PUT / multipart
    *initiate* (--s3sse / --s3sseckey / --s3ssekmskey). SSE-S3/KMS go on
    the initiate request only; parts and downloads need only SSE-C."""
    h = {}
    if cfg.s3_sse_kms_key_id:
        h["x-amz-server-side-encryption"] = "aws:kms"
        h["x-amz-server-side-encryption-aws-kms-key-id"] = \
            cfg.s3_sse_kms_key_id
    elif cfg.s3_sse:
        h["x-amz-server-side-encryption"] = "AES256"
    h.update(_sse_c_headers(cfg))
    return h or None


def _upload_init_headers(cfg) -> "dict | None":
    """Headers for single PUT: SSE + inline ACL grants (--s3aclputinl) +
    checksum algorithm announcement (SDK-style header; the actual
    x-amz-checksum-<algo> value comes from _body_headers)."""
    h = dict(_sse_headers(cfg) or {})
    if cfg.do_s3_acl_put_inline and cfg.s3_acl_grantee:
        h.update(_acl_headers(cfg))
    if cfg.s3_checksum_algo:
        h["x-amz-sdk-checksum-algorithm"] = cfg.s3_checksum_algo.upper()
    return h or None


def _mpu_init_headers(cfg) -> "dict | None":
    """CreateMultipartUpload headers: like single PUT, but the checksum
    algorithm is announced via x-amz-checksum-algorithm (the header that
    CreateMultipartUpload actually accepts)."""
    h = dict(_sse_headers(cfg) or {})
    if cfg.do_s3_acl_put_inline and cfg.s3_acl_grantee:
        h.update(_acl_headers(cfg))
    if cfg.s3_checksum_algo:
        h["x-amz-checksum-algorithm"] = cfg.s3_checksum_algo.upper()
    return h or None


def _body_headers(cfg, body: bytes, base: "dict | None") -> "dict | None":
    """Per-payload headers: base + x-amz-checksum-<algo> of this body."""
    if not cfg.s3_checksum_algo:
        return base
    from ..toolkits.s3_tk import build_checksum_headers
    h = dict(base or {})
    h.update(build_checksum_headers(cfg.s3_checksum_algo, body))
    return h


def _obj_tagging(worker, phase: BenchPhase) -> None:
    """Object tagging put/get/del phases (--s3otag; verify with
    --s3otagverify) — reference: :7109-7204."""
    client = _client(worker)
    cfg = worker.cfg
    for bucket, key in _iter_entries(worker):
        worker.check_interruption_request(force=True)
        with worker.oplog(phase.name.lower(), f"{bucket}/{key}"):
            t0 = time.perf_counter_ns()
            if phase == BenchPhase.PUT_OBJ_MD:
                client.put_object_tagging(bucket, key, _BENCH_TAGS)
            elif phase == BenchPhase.GET_OBJ_MD:
                tags = client.get_object_tagging(bucket, key)
                if cfg.do_s3_object_tagging_verify and tags != _BENCH_TAGS:
                    raise WorkerException(
                        f"object tag verification failed for {key}: {tags}")
            else:  # DEL_OBJ_MD
                client.delete_object_tagging(bucket, key)
            worker.entries_latency_histo.add_latency(
                (time.perf_counter_ns() - t0) // 1000)
        worker.live_ops.num_entries_done += 1


def _bucket_metadata(worker, phase: BenchPhase) -> None:
    """Bucket-level metadata phases: tagging, versioning, object-lock
    config, each optional + verifiable (reference: bucket MD phases +
    --s3btag/--s3bversion/--s3olockcfg and their verify flags)."""
    cfg = worker.cfg
    client = _client(worker)
    ndst = max(1, cfg.num_dataset_threads)
    got_work = False
    for idx, bucket in enumerate(cfg.paths):
        if idx % ndst != worker.rank % ndst:
            continue
        got_work = True
        worker.check_interruption_request(force=True)
        with worker.oplog(phase.name.lower(), bucket):
            t0 = time.perf_counter_ns()
            if phase == BenchPhase.PUT_BUCKET_MD:
                if cfg.run_s3_bucket_tagging:
                    client.put_bucket_tagging(bucket, _BENCH_TAGS)
                if cfg.run_s3_bucket_versioning:
                    client.put_bucket_versioning(bucket, enabled=True)
                if cfg.run_s3_object_lock_cfg:
                    client.put_object_lock_configuration(bucket)
            elif phase == BenchPhase.GET_BUCKET_MD:
                if cfg.run_s3_bucket_tagging:
                    tags = client.get_bucket_tagging(bucket)
                    if cfg.do_s3_bucket_tagging_verify \
                            and tags != _BENCH_TAGS:
                        raise WorkerException(
                            f"bucket tag verification failed: {tags}")
                if cfg.run_s3_bucket_versioning:
                    status = client.get_bucket_versioning(bucket)
                    if cfg.do_s3_bucket_versioning_verify \
                            and status != "Enabled":
                        raise WorkerException(
                            f"bucket versioning verification failed: "
                            f"{status!r}")
                if cfg.run_s3_object_lock_cfg:
                    mode = client.get_object_lock_configuration(bucket)
                    if cfg.do_s3_object_lock_cfg_verify and not mode:
                        raise WorkerException(
                            "object-lock configuration verification failed")
            else:  # DEL_BUCKET_MD (reference: LocalWorker.cpp:3883-3892
                  # suspends versioning / clears lock cfg on cleanup)
                if cfg.run_s3_bucket_tagging:
                    client.delete_bucket_tagging(bucket)
                if cfg.run_s3_bucket_versioning:
                    client.put_bucket_versioning(bucket, enabled=False)
                if cfg.run_s3_object_lock_cfg:
                    client.put_object_lock_configuration(bucket, mode="",
                                                         days=0)
            worker.entries_latency_histo.add_latency(
                (time.perf_counter_ns() - t0) // 1000)
        worker.live_ops.num_entries_done += 1
    worker.got_phase_work = got_work
