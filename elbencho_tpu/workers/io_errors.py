"""Data-plane fault tolerance: storage-error classifier + per-op retry.

PR 2 gave the *control plane* a transient-vs-permanent discipline
(`service/fault_tolerance.py`); this module applies the same idiom to the
*data plane* — the per-op storage I/O of the worker loops — so a transient
storage hiccup (`EINTR`, `EAGAIN`, `ETIMEDOUT`, a short read, `EIO` on a
network filesystem) no longer aborts a whole multi-hour phase, while a
permanent condition (`ENOSPC`, `EROFS`, `EBADF`, ...) still fails fast.

Classifier table (docs/fault-tolerance.md):

==============  ===========  =============================================
error           class        rationale
==============  ===========  =============================================
EINTR           transient    interrupted syscall; retry is the POSIX idiom
EAGAIN          transient    transient resource pressure
ETIMEDOUT       transient    per-op deadline (--iotimeout) or netfs timeout
short read/wr   transient    racing truncation/eof settles, netfs hiccup
EIO on netfs    transient    NFS/FUSE/parallel-fs transport errors surface
                             as EIO; local-disk EIO stays permanent
ESTALE/EREMOTEIO transient   stale NFS handle / remote I/O hiccup
ENOSPC EROFS    permanent    retrying cannot create space / writability
EBADF EINVAL    permanent    programming/setup error
ENOENT EACCES   permanent    namespace/permission problems don't heal
everything else permanent    fail-fast default (classify-by-allowlist)
==============  ===========  =============================================

Retry shape: ``--ioretries N`` attempts on top of the first try, jittered
exponential backoff (the shared ``RetryPolicy``), all backoff drawing from
one per-phase ``--ioretrybudget`` seconds account (``RetryBudget``) so a
dying device converges to an error instead of retrying forever. The
default of 0 retries preserves today's fail-fast behavior bit for bit.
"""

from __future__ import annotations

import errno
import os
import random

from ..service.fault_tolerance import RetryBudget, RetryPolicy

#: always-transient errnos (see the classifier table above)
TRANSIENT_ERRNOS = frozenset({
    errno.EINTR, errno.EAGAIN, errno.ETIMEDOUT, errno.ESTALE,
    getattr(errno, "EREMOTEIO", 121),
})

#: errnos that are never retried, even on a network filesystem
PERMANENT_ERRNOS = frozenset({
    errno.ENOSPC, errno.EROFS, errno.EBADF, errno.EDQUOT, errno.EINVAL,
    errno.ENOENT, errno.EACCES, errno.EPERM, errno.EISDIR, errno.ENOTDIR,
})

#: /proc/mounts fstypes treated as network/parallel filesystems, where
#: EIO usually means a transport hiccup rather than dying media
NETFS_TYPES = frozenset({
    "nfs", "nfs4", "cifs", "smb3", "smbfs", "9p", "afs", "ceph",
    "lustre", "beegfs", "gpfs", "glusterfs", "panfs", "pvfs2",
    "virtiofs", "fuse", "fuse.gcsfuse", "fuse.s3fs", "fuse.sshfs",
    "fuse.juicefs",
})

_mount_cache: "dict[str, bool] | None" = None


class ShortIOError(OSError):
    """A read/write moved fewer bytes than requested — transient (racing
    truncation settles; netfs hiccups heal). Message matches the worker
    loops' historic short-I/O error text so ``--ioretries 0`` output is
    byte-for-byte identical to the pre-retry behavior."""

    def __init__(self, is_read: bool, offset: int, got: int, want: int):
        self.is_read = is_read
        self.offset = offset
        self.got = got
        self.want = want
        super().__init__(errno.EIO,
                         f"short {'read' if is_read else 'write'} at "
                         f"offset {offset}: {got} != {want}")

    def __str__(self) -> str:  # exact parity with the historic message
        return (f"short {'read' if self.is_read else 'write'} at "
                f"offset {self.offset}: {self.got} != {self.want}")


def _load_netfs_mounts() -> "list[tuple[str, bool]]":
    """[(mountpoint, is_netfs)] sorted longest-mountpoint-first so a
    longest-prefix match resolves nested mounts correctly."""
    mounts: "list[tuple[str, bool]]" = []
    try:
        with open("/proc/self/mounts") as f:
            for line in f:
                parts = line.split()
                if len(parts) < 3:
                    continue
                mnt = parts[1].replace("\\040", " ")
                fstype = parts[2]
                is_net = (fstype in NETFS_TYPES
                          or fstype.split(".", 1)[0] == "fuse")
                mounts.append((mnt, is_net))
    except OSError:
        pass
    mounts.sort(key=lambda m: len(m[0]), reverse=True)
    return mounts


def is_netfs_path(path: str) -> bool:
    """Whether path lives on a network/parallel filesystem (longest
    mountpoint prefix match over /proc/self/mounts, cached)."""
    global _mount_cache
    if not path:
        return False
    if _mount_cache is None:
        _mount_cache = {}
    path = os.path.abspath(path)
    hit = _mount_cache.get(path)
    if hit is not None:
        return hit
    result = False
    for mnt, is_net in _load_netfs_mounts():
        if path == mnt or path.startswith(mnt.rstrip("/") + "/") \
                or mnt == "/":
            result = is_net
            break
    _mount_cache[path] = result
    return result


def reset_netfs_cache() -> None:
    global _mount_cache
    _mount_cache = None


def classify_io_error(err: BaseException, path: str = "",
                      netfs: "bool | None" = None) -> str:
    """'transient' (a retry plausibly succeeds) or 'permanent' (abort
    now). netfs overrides the path-based network-filesystem detection —
    object/HDFS callers pass True, their transport is a network by
    definition."""
    if isinstance(err, ShortIOError):
        return "transient"
    if not isinstance(err, OSError) or err.errno is None:
        return "permanent"
    if err.errno in PERMANENT_ERRNOS:
        return "permanent"
    if err.errno in TRANSIENT_ERRNOS:
        return "transient"
    if err.errno == errno.EIO:
        on_net = netfs if netfs is not None else is_netfs_path(path)
        return "transient" if on_net else "permanent"
    return "permanent"


class IoRetrier:
    """Per-worker retry driver for storage ops, sharing PR 2's
    ``RetryPolicy``/``RetryBudget`` idiom. Counts every retry into the
    worker's ``io_retries``/``io_retry_usec`` audit counters (plumbed to
    JSON//metrics via ``PATH_AUDIT_COUNTERS``) and checks the worker's
    interruption flag between backoff slices so Ctrl-C/time limits stay
    responsive even mid-backoff."""

    #: backoff sleep slice so interrupts are noticed promptly
    _SLEEP_SLICE_SECS = 0.1

    def __init__(self, worker, policy: RetryPolicy):
        self.worker = worker
        self.policy = policy
        self.budget = RetryBudget(policy.budget_secs)
        # deterministic per-rank jitter stream (reproducible chaos runs)
        self._rng = random.Random(worker.rank)
        self._consec = 0

    def reset(self) -> None:
        """Per-phase reset (the budget is a per-phase account)."""
        self.budget.reset()
        self._consec = 0

    def should_retry(self, err: BaseException, path: str = "",
                     netfs: "bool | None" = None,
                     attempt: "int | None" = None) -> bool:
        """attempt: explicit per-op retry count for callers that
        interleave many in-flight ops (the fused ring) — the shared
        consecutive counter would let one op's retry falsely exhaust (or
        another op's success falsely reset) a sibling's allowance."""
        if self.policy.num_retries <= 0:
            return False
        done = self._consec if attempt is None else attempt
        if done >= self.policy.num_retries:
            return False
        return classify_io_error(err, path, netfs) == "transient"

    def note_success(self) -> None:
        self._consec = 0

    def backoff(self, attempt: "int | None" = None) -> None:
        """One jittered-backoff sleep drawn from the per-phase budget;
        raises the budget exhaustion as a StopIteration-free RuntimeError
        equivalent — the caller re-raises the original error instead."""
        import time
        done = self._consec if attempt is None else attempt
        delay = self.policy.backoff_delay(done, self._rng)
        if not self.budget.try_spend(delay):
            raise IoRetryBudgetExhausted(
                f"--ioretrybudget exhausted: {self.budget.spent_secs:.1f}s "
                f"of I/O retry backoff already spent this phase")
        if attempt is None:
            self._consec += 1
        self.worker.io_retries += 1
        self.worker.io_retry_usec += int(delay * 1_000_000)
        tracer = getattr(self.worker, "_tracer", None)
        t0 = tracer.now_ns() if tracer is not None else 0
        remaining = delay
        while remaining > 0:
            self.worker.check_interruption_flag_only()
            slice_ = min(self._SLEEP_SLICE_SECS, remaining)
            time.sleep(slice_)
            remaining -= slice_
        if tracer is not None:  # --tracefile: backoff visible per op
            tracer.record("io_retry", "fault", t0,
                          (tracer.now_ns() - t0) // 1000,
                          rank=self.worker.rank, sampled=True)

    def run(self, op, path: str = "", netfs: "bool | None" = None):
        """Run op() with transient-error retries. The final failure
        re-raises the ORIGINAL error so ``--ioretries 0`` (where this is
        never even called) and exhausted-retry output look identical."""
        while True:
            try:
                result = op()
            except Exception as err:  # noqa: BLE001 - classified below
                if not self.should_retry(err, path, netfs):
                    raise
                try:
                    self.backoff()
                except IoRetryBudgetExhausted:
                    raise err from None
                continue
            self.note_success()
            return result


class IoRetryBudgetExhausted(Exception):
    """Internal: the per-phase backoff budget ran dry; the caller
    re-raises the original storage error."""


def make_io_retrier(worker) -> "IoRetrier | None":
    """Build the worker's retrier from --ioretries/--ioretrybudget
    (None when retries are disabled — the hot loops then skip every
    retry-related branch, preserving exact fail-fast behavior)."""
    cfg = worker.cfg
    if getattr(cfg, "io_num_retries", 0) <= 0:
        return None
    policy = RetryPolicy(num_retries=cfg.io_num_retries,
                         budget_secs=max(cfg.io_retry_budget_secs, 0))
    return IoRetrier(worker, policy)
