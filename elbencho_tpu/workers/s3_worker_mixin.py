"""S3/object-storage phase dispatch (placeholder until the S3 front-end
lands; reference surface: LocalWorker.cpp:3822-7291, 25 bench phases)."""

from __future__ import annotations

from .shared import WorkerException


def dispatch_s3_phase(worker, phase) -> None:
    raise WorkerException(
        "S3/object storage mode is not available yet in this build")
