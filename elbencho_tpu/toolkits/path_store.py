"""PathStore: custom-tree file lists and worker sublist math.

Reference: source/PathStore.{h,cpp} — treefile parsing ("d <path>" dir lines,
"f <size> <path>" file lines, '#' comments, optional "# encoding=base64"
header so names with newlines survive, PathStore.h:12-16), sorting, shuffle,
and the worker sublist computations: non-shared (whole files round-robin by
aggregate size), shared (block-granular slices of large files), and
shared-round-robin (--treeroundrob). The --sharesize threshold splits files
into a shared set (sliced by blocks) and non-shared set (PathStore.h:107-112).
"""

from __future__ import annotations

import base64
import random
from dataclasses import dataclass, field

TREEFILE_COMMENT_CHAR = "#"
TREEFILE_BASE64_HEADER = "# encoding=base64"
DIR_LINE_PREFIX = "d"
FILE_LINE_PREFIX = "f"


@dataclass
class PathStoreElem:
    path: str
    total_len: int = 0      # total size of the file/object
    range_start: int = 0    # slice offset (shared files)
    range_len: int = 0      # slice length (shared files)


@dataclass
class PathStore:
    elems: "list[PathStoreElem]" = field(default_factory=list)
    block_size: int = 1

    # -- loading ------------------------------------------------------------

    @staticmethod
    def _treefile_is_base64(text: str) -> bool:
        for line in text.splitlines():
            if line.startswith(TREEFILE_BASE64_HEADER):
                return True
            if line and not line.startswith(TREEFILE_COMMENT_CHAR):
                break
        return False

    @classmethod
    def _decode_name(cls, name: str, is_b64: bool) -> str:
        if not is_b64:
            return name
        return base64.b64decode(name).decode("utf-8", errors="surrogateescape")

    def load_dirs_from_text(self, text: str) -> None:
        """Parse "d <relative_path>" lines; others ignored
        (reference: PathStore.cpp:27-80)."""
        is_b64 = self._treefile_is_base64(text)
        for line in text.splitlines():
            parts = line.split(maxsplit=1)
            if len(parts) != 2 or parts[0] != DIR_LINE_PREFIX:
                continue
            self.elems.append(PathStoreElem(self._decode_name(parts[1], is_b64)))

    def load_files_from_text(self, text: str, min_size: int = 0,
                             max_size: "int | None" = None,
                             round_up_size: int = 0) -> None:
        """Parse "f <size_in_bytes> <relative_path>" lines with size filter
        and optional round-up (reference: PathStore.cpp:85-170)."""
        is_b64 = self._treefile_is_base64(text)
        for line in text.splitlines():
            parts = line.split(maxsplit=2)
            if len(parts) != 3 or parts[0] != FILE_LINE_PREFIX:
                continue
            size = int(parts[1])
            if size < min_size or (max_size is not None and size > max_size):
                continue
            if round_up_size and size % round_up_size:
                size += round_up_size - (size % round_up_size)
            self.elems.append(PathStoreElem(
                self._decode_name(parts[2], is_b64), total_len=size,
                range_start=0, range_len=size))

    def load_dirs_from_file(self, path: str) -> None:
        with open(path, "r", encoding="utf-8", errors="surrogateescape") as f:
            self.load_dirs_from_text(f.read())

    def load_files_from_file(self, path: str, min_size: int = 0,
                             max_size: "int | None" = None,
                             round_up_size: int = 0) -> None:
        with open(path, "r", encoding="utf-8", errors="surrogateescape") as f:
            self.load_files_from_text(f.read(), min_size, max_size, round_up_size)

    @staticmethod
    def generate_file_line(path: str, file_size: int) -> str:
        return f"{FILE_LINE_PREFIX} {file_size} {path}"

    @staticmethod
    def generate_dir_line(path: str) -> str:
        return f"{DIR_LINE_PREFIX} {path}"

    # -- ordering -----------------------------------------------------------

    def sort_by_path_len(self) -> None:
        self.elems.sort(key=lambda e: (len(e.path), e.path))

    def sort_by_file_size(self) -> None:
        self.elems.sort(key=lambda e: (e.total_len, e.path))

    def random_shuffle(self, seed: "int | None" = None) -> None:
        random.Random(seed).shuffle(self.elems)

    # -- worker sublists (SURVEY.md section 2.4 "custom-tree sharding") ------

    def get_worker_sublist_non_shared(self, worker_rank: int,
                                      num_dataset_threads: int) -> "PathStore":
        """Whole files distributed by greedy least-loaded assignment with a
        deterministic tie-break, so every worker gets a near-equal byte share
        (reference: getWorkerSublistNonShared, PathStore.h:53)."""
        loads = [0] * num_dataset_threads
        out = PathStore(block_size=self.block_size)
        # deterministic: process big files first for balance
        order = sorted(range(len(self.elems)),
                       key=lambda i: (-self.elems[i].total_len, i))
        for i in order:
            tgt = min(range(num_dataset_threads), key=lambda r: (loads[r], r))
            loads[tgt] += max(self.elems[i].total_len, 1)
            if tgt == worker_rank:
                out.elems.append(self.elems[i])
        # keep stable original ordering within the worker's share
        out.elems.sort(key=lambda e: e.path)
        return out

    def get_worker_sublist_shared(self, worker_rank: int,
                                  num_dataset_threads: int) -> "PathStore":
        """Block-granular contiguous slices: the store's total block count is
        divided evenly; each worker receives a contiguous run of blocks which
        maps to (possibly partial) per-file ranges
        (reference: getWorkerSublistShared, PathStore.h:55)."""
        bs = self.block_size
        file_blocks = [max(1, (e.total_len + bs - 1) // bs) for e in self.elems]
        total_blocks = sum(file_blocks)
        base, rem = divmod(total_blocks, num_dataset_threads)
        start_block = worker_rank * base + min(worker_rank, rem)
        my_blocks = base + (1 if worker_rank < rem else 0)
        end_block = start_block + my_blocks

        out = PathStore(block_size=bs)
        cursor = 0
        for elem, nblocks in zip(self.elems, file_blocks):
            file_start, file_end = cursor, cursor + nblocks
            cursor = file_end
            lo = max(start_block, file_start)
            hi = min(end_block, file_end)
            if lo >= hi:
                continue
            range_start = (lo - file_start) * bs
            range_len = min((hi - lo) * bs, elem.total_len - range_start)
            out.elems.append(PathStoreElem(elem.path, elem.total_len,
                                           range_start, range_len))
        return out

    def get_worker_sublist_shared_round_robin(self, worker_rank: int,
                                              num_dataset_threads: int
                                              ) -> "PathStore":
        """Round-robin block assignment (--treeroundrob): worker takes every
        num_dataset_threads-th block. Represented as per-file strided ranges;
        consumers use OffsetGenStrided over each file's local block index
        (reference: getWorkerSublistSharedRoundRobin, PathStore.h:57)."""
        bs = self.block_size
        out = PathStore(block_size=bs)
        global_block = 0
        for elem in self.elems:
            nblocks = max(1, (elem.total_len + bs - 1) // bs)
            # blocks of this file whose global index % threads == rank
            first = None
            count = 0
            for b in range(nblocks):
                if (global_block + b) % num_dataset_threads == worker_rank:
                    if first is None:
                        first = b
                    count += 1
            global_block += nblocks
            if first is None:
                continue
            range_start = first * bs
            range_len = min(count * bs, elem.total_len - range_start)
            out.elems.append(PathStoreElem(elem.path, elem.total_len,
                                           range_start, range_len))
        return out

    # -- misc ---------------------------------------------------------------

    def split_by_share_size(self, share_size: int
                            ) -> "tuple[PathStore, PathStore]":
        """(non_shared, shared): files >= share_size go to the shared
        (block-sliced) set (reference: --sharesize, PathStore.h:107-112)."""
        non_shared = PathStore(block_size=self.block_size)
        shared = PathStore(block_size=self.block_size)
        for e in self.elems:
            (shared if e.total_len >= share_size else non_shared).elems.append(e)
        return non_shared, shared

    @property
    def num_paths(self) -> int:
        return len(self.elems)

    @property
    def total_bytes(self) -> int:
        return sum(e.range_len or e.total_len for e in self.elems)
