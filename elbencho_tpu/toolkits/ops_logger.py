"""Per-operation JSONL trace (``--opslog``).

Reference: source/toolkits/OpsLogger.{h,cpp} — one JSON line per record with
date, worker_rank, op_name, entry_name, offset, length, is_finished,
is_error; pre- and post-op records; optional flock for shared log files
(``--opsloglock``); near-zero overhead when disabled (OpsLogger.h:19-36).
"""

from __future__ import annotations

import fcntl
import json
import os
import time


class OpsLogger:
    def __init__(self, path: str, worker_rank: int, use_lock: bool = False):
        self.worker_rank = worker_rank
        self.use_lock = use_lock
        self._fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                           0o644)

    @property
    def fd(self) -> int:
        """Raw fd for the native engine's in-loop block records."""
        return self._fd

    def _write(self, record: dict) -> None:
        line = (json.dumps(record, separators=(",", ":")) + "\n").encode()
        if self.use_lock:
            fcntl.flock(self._fd, fcntl.LOCK_EX)
            try:
                os.write(self._fd, line)
            finally:
                fcntl.flock(self._fd, fcntl.LOCK_UN)
        else:
            os.write(self._fd, line)

    def _record(self, op_name: str, entry_name: str, offset: int,
                length: int, is_finished: bool, is_error: bool) -> dict:
        return {
            "date": time.strftime("%Y%m%dT%H%M%S") + f".{time.time_ns() % 1_000_000_000:09d}",
            "worker_rank": self.worker_rank,
            "op_name": op_name,
            "entry_name": entry_name,
            "offset": offset,
            "length": length,
            "is_finished": is_finished,
            "is_error": is_error,
        }

    def log_op_pre(self, op_name: str, entry_name: str = "",
                   offset: int = 0, length: int = 0) -> None:
        self._write(self._record(op_name, entry_name, offset, length,
                                 is_finished=False, is_error=False))

    def log_op(self, op_name: str, entry_name: str = "", offset: int = 0,
               length: int = 0, is_error: bool = False) -> None:
        self._write(self._record(op_name, entry_name, offset, length,
                                 is_finished=True, is_error=is_error))

    def logged_op(self, op_name: str, entry_name: str = "",
                  offset: int = 0, length: int = 0) -> "_LoggedOp":
        """Context manager writing the pre record on entry and the post
        record on exit — with is_error=True when the body raises, or when
        the body sets ``ctx.error = True`` (for swallowed failures)."""
        return _LoggedOp(self, op_name, entry_name, offset, length)

    def close(self) -> None:
        if self._fd >= 0:
            os.close(self._fd)
            self._fd = -1


class _LoggedOp:
    __slots__ = ("_logger", "_args", "error")

    def __init__(self, logger: "OpsLogger | None", op_name: str,
                 entry_name: str, offset: int, length: int):
        self._logger = logger
        self._args = (op_name, entry_name, offset, length)
        self.error = False

    def __enter__(self) -> "_LoggedOp":
        if self._logger is not None:
            self._logger.log_op_pre(*self._args)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._logger is not None:
            self._logger.log_op(*self._args,
                                is_error=self.error or exc_type is not None)
        return False


#: shared no-op instance for workers running without --opslog
def null_logged_op(*_args, **_kwargs) -> _LoggedOp:
    return _LoggedOp(None, "", "", 0, 0)
