"""Bounded TPU reachability probe — the importable core of `tools/tpu-probe`.

The axon tunnel on this box makes `jax.devices()` block FOREVER when the
tunnel is down (backend init walks every platform), so reachability must
always be checked in a bounded subprocess, never in-process. This module
is the single implementation of that CHECK (one bounded probe and what
counts as "up"), shared by:

- `tools/tpu-probe` (operator CLI: one-shot JSON status, `--wait` mode,
  `--exec` hook to convert any tunnel-up window into a fresh capture)
- `bench.py` (driver benchmark), which wraps probe_once in its OWN retry
  loop rather than wait_until_up: its cadence is deliberately different
  (exponential backoff clamped to the bench's global token budget, and a
  timeline format embedded in the never-null failure record)
- the watcher pattern `tpu-probe --wait --exec "python bench.py"`

Reference analogue: elbencho has no tunnel, but its service-mode master
polls every service for readiness before a run (RemoteWorker.cpp
checkServiceVersions); this is the same "don't start until the device
plane answers" discipline applied to the PJRt backend.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

#: platforms that count as the real tunneled TPU on this box
TPU_PLATFORMS = ("tpu", "axon")

_PROBE_SNIPPET = (
    "import jax; d = jax.devices(); "
    "print(d[0].platform, len(d))"
)


def utc_now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


class ProbeResult(dict):
    """Plain dict with attribute sugar; JSON-serializable as-is."""

    @property
    def up(self) -> bool:
        return bool(self.get("up"))

    @property
    def platform(self) -> "str | None":
        return self.get("platform")


def probe_once(timeout_s: float = 120.0, env: "dict | None" = None,
               require_tpu: bool = True,
               on_spawn=None) -> ProbeResult:
    """One bounded reachability check.

    Returns a ProbeResult with keys: up, platform, device_count,
    elapsed_s, utc and (on failure) outcome ("timeout"/"error") + error.
    ``require_tpu`` demands a TPU_PLATFORMS backend; with False any live
    backend (e.g. the CPU self-test env) counts as up.
    ``on_spawn`` is called with the Popen object right after spawn so a
    caller's signal handler can kill the child (bench.py does this).
    """
    t0 = time.monotonic()
    rec = ProbeResult(up=False, platform=None, device_count=None,
                      utc=utc_now())
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_SNIPPET],
        env=dict(os.environ) if env is None else env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    if on_spawn is not None:
        on_spawn(proc)
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        rec["outcome"] = "timeout"
        rec["error"] = f"probe subprocess exceeded {timeout_s:.0f}s"
        rec["elapsed_s"] = round(time.monotonic() - t0, 1)
        return rec
    rec["elapsed_s"] = round(time.monotonic() - t0, 1)
    if proc.returncode != 0:
        rec["outcome"] = "error"
        rec["error"] = err.strip()[-500:]
        return rec
    try:
        platform, count = out.split()
        platform = platform.strip().lower()
        count = int(count)
    except ValueError:
        rec["outcome"] = "error"
        rec["error"] = f"unparseable probe output: {out[:200]!r}"
        return rec
    rec["platform"] = platform
    rec["device_count"] = count
    if require_tpu and platform not in TPU_PLATFORMS:
        rec["outcome"] = "wrong_platform"
        rec["error"] = (f"default backend is {platform!r}, not a TPU "
                        f"({'/'.join(TPU_PLATFORMS)})")
        return rec
    rec["up"] = True
    rec["outcome"] = "ok"
    return rec


def wait_until_up(window_s: float, interval_s: float = 60.0,
                  attempt_timeout_s: float = 120.0,
                  env: "dict | None" = None, require_tpu: bool = True,
                  log=None) -> ProbeResult:
    """Poll until the backend answers or ``window_s`` is spent.

    Returns the final ProbeResult augmented with "attempts" (full
    timeline) and "waited_s". The attempt cadence is one probe per
    ``interval_s`` measured from probe START, so a fast failure does not
    turn the wait into a busy loop and a slow timeout does not stretch
    the cadence beyond interval + attempt_timeout.
    """
    t_start = time.monotonic()
    attempts = []
    while True:
        t_probe = time.monotonic()
        res = probe_once(attempt_timeout_s, env=env, require_tpu=require_tpu)
        attempts.append({k: res.get(k) for k in
                         ("utc", "outcome", "elapsed_s", "platform", "error")
                         if res.get(k) is not None})
        if log is not None:
            log(f"probe {len(attempts)}: {res.get('outcome')} "
                f"({res.get('elapsed_s')}s)")
        if res.up:
            break
        remaining = window_s - (time.monotonic() - t_start)
        if remaining <= 0:
            break
        sleep_s = interval_s - (time.monotonic() - t_probe)
        if sleep_s > 0:
            time.sleep(min(sleep_s, max(remaining, 0)))
    res["attempts"] = attempts
    res["waited_s"] = round(time.monotonic() - t_start, 1)
    return res


def main(argv: "list[str] | None" = None) -> int:
    """CLI entry for tools/tpu-probe. Exit 0 when up, 1 when not, 2 on
    bad usage. Always prints one JSON status object (unless --quiet)."""
    import argparse
    ap = argparse.ArgumentParser(
        prog="tpu-probe",
        description="Bounded TPU-tunnel reachability probe with optional "
                    "wait-until-up mode and on-up command hook.")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-attempt probe timeout in seconds (default 120)")
    ap.add_argument("--wait", action="store_true",
                    help="poll until the TPU answers or --window is spent")
    ap.add_argument("--window", type=float, default=3600.0,
                    help="total wait window for --wait, seconds (default 3600)")
    ap.add_argument("--interval", type=float, default=60.0,
                    help="probe cadence for --wait, seconds (default 60)")
    ap.add_argument("--exec", dest="exec_cmd", default=None,
                    help="shell command to run once the TPU is up (its rc "
                         "becomes the exit code); typical use: "
                         "--wait --exec 'python bench.py'")
    ap.add_argument("--any-backend", action="store_true",
                    help="accept any live jax backend, not just TPU "
                         "(harness self-test)")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the JSON status line")
    args = ap.parse_args(argv)

    def log(msg):
        print(f"# {msg}", file=sys.stderr)

    if args.wait:
        res = wait_until_up(args.window, interval_s=args.interval,
                            attempt_timeout_s=args.timeout,
                            require_tpu=not args.any_backend, log=log)
    else:
        res = probe_once(args.timeout, require_tpu=not args.any_backend)
    if not args.quiet:
        print(json.dumps(res), flush=True)
    if not res.up:
        return 1
    if args.exec_cmd:
        log(f"TPU up — running: {args.exec_cmd}")
        return subprocess.call(args.exec_cmd, shell=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
