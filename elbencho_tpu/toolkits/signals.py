"""Fault-signal diagnostics.

Reference: source/toolkits/SignalTk.{h,cpp} — fault handlers print a
backtrace to the console and write /tmp/elbencho_fault_trace.txt
(SignalTk.cpp:25-60); SIGINT blocking for worker threads is handled by the
coordinator's handler instead (Python delivers signals to the main thread
only, so per-thread blocking is unnecessary).
"""

from __future__ import annotations

import faulthandler
import getpass

FAULT_TRACE_PATH_TEMPLATE = "/tmp/elbencho_tpu_{user}_fault_trace.txt"

_trace_file = None


def register_fault_handlers() -> str:
    """Enable faulthandler for SIGSEGV/SIGFPE/SIGABRT/SIGBUS: tracebacks of
    all threads go to a per-user trace file (faulthandler supports a single
    sink; the path is logged at startup so a crashed console run points
    somewhere). Returns the trace file path."""
    global _trace_file
    path = FAULT_TRACE_PATH_TEMPLATE.format(user=getpass.getuser())
    if _trace_file is None:
        try:
            _trace_file = open(path, "w")
            faulthandler.enable(file=_trace_file, all_threads=True)
            from . import logger
            logger.log(logger.LOG_VERBOSE,
                       f"fault trace file: {path}")
        except OSError:
            faulthandler.enable()  # stderr only
    return path
