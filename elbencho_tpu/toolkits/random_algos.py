"""PRNG quartet (reference: source/toolkits/random/RandAlgo*.h).

User-selectable random generators, same tiers as the reference
(RandAlgoSelectorTk.h:12-24):
  strong           - MT19937 (RandAlgoMT19937.h)
  balanced_single  - xoshiro256** (RandAlgoXoshiro256ss.h)
  balanced         - xoshiro256++ N-way (RandAlgoXoshiro256ppSIMD.h); here the
                     vectorization is numpy-based for buffer fills
  fast             - golden-prime multiplicative (RandAlgoGoldenPrime.h:
                     multiply-shift, reseeds every 256 KiB of output)

Used for ``--randalgo`` (offset generation) and ``--blockvaralgo`` (buffer
refill / block variance). The hot-path buffer fills go through
``fill_buffer``, which uses numpy vectorization; the C++ ioengine has its own
native implementations of the same algorithms.
"""

from __future__ import annotations

import random as _pyrandom

import numpy as np

_MASK64 = (1 << 64) - 1

# golden-ratio prime multiplier (fast/weak tier). The generator emits
# value*=prime; out = value rotated, and reseeds every 256 KiB like the
# reference's RandAlgoGoldenPrime.h.
_GOLDEN_PRIME = 0x9E3779B97F4A7C15
_GOLDEN_RESEED_BYTES = 256 * 1024


class RandAlgo:
    """Interface: next64() -> int in [0, 2^64); fill_buffer(n) -> bytes."""

    name = "base"

    def next64(self) -> int:
        raise NotImplementedError

    def next64_batch(self, n: int) -> np.ndarray:
        """n draws as a uint64 array. The default loops next64 (exact
        sequence); the fast tier overrides with closed-form vector math so
        random offset generation can feed the native engine in bulk."""
        out = np.empty(n, dtype=np.uint64)
        for i in range(n):
            out[i] = self.next64()
        return out

    def next_in_range(self, lo: int, hi: int) -> int:
        """Uniform value in [lo, hi] (inclusive), like RandAlgoRange.h."""
        span = hi - lo + 1
        return lo + (self.next64() % span)

    def fill_buffer(self, num_bytes: int) -> bytes:
        out = bytearray()
        while len(out) < num_bytes:
            out += self.next64().to_bytes(8, "little")
        return bytes(out[:num_bytes])


class RandAlgoMT19937(RandAlgo):
    """'strong' tier: Mersenne Twister."""

    name = "strong"

    def __init__(self, seed: int | None = None):
        self._rng = _pyrandom.Random(seed)

    def next64(self) -> int:
        return self._rng.getrandbits(64)

    def fill_buffer(self, num_bytes: int) -> bytes:
        return self._rng.randbytes(num_bytes)


def _rotl(x: int, k: int) -> int:
    return ((x << k) | (x >> (64 - k))) & _MASK64


def _splitmix64_stream(seed: int, n: int) -> "list[int]":
    out = []
    state = seed & _MASK64
    for _ in range(n):
        state = (state + 0x9E3779B97F4A7C15) & _MASK64
        z = state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK64
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK64
        out.append(z ^ (z >> 31))
    return out


class RandAlgoXoshiro256ss(RandAlgo):
    """'balanced_single' tier: xoshiro256** scalar."""

    name = "balanced_single"

    def __init__(self, seed: int | None = None):
        if seed is None:
            seed = _pyrandom.getrandbits(64)
        self._s = _splitmix64_stream(seed, 4)

    def next64(self) -> int:
        s = self._s
        result = (_rotl((s[1] * 5) & _MASK64, 7) * 9) & _MASK64
        t = (s[1] << 17) & _MASK64
        s[2] ^= s[0]
        s[3] ^= s[1]
        s[1] ^= s[2]
        s[0] ^= s[3]
        s[2] ^= t
        s[3] = _rotl(s[3], 45)
        return result


class RandAlgoXoshiro256pp(RandAlgo):
    """'balanced' tier: xoshiro256++; fill_buffer is vectorized via numpy
    (the reference vectorizes N lanes with compiler auto-vectorization,
    RandAlgoXoshiro256ppSIMD.h / Makefile:72-77)."""

    name = "balanced"
    LANES = 8

    def __init__(self, seed: int | None = None):
        if seed is None:
            seed = _pyrandom.getrandbits(64)
        states = _splitmix64_stream(seed, 4 * self.LANES)
        self._s = np.array(states, dtype=np.uint64).reshape(4, self.LANES)
        self._scalar = RandAlgoXoshiro256ss(seed)

    def next64(self) -> int:
        return self._scalar.next64()

    def _next_vec(self) -> np.ndarray:
        s = self._s
        with np.errstate(over="ignore"):
            tot = s[0] + s[3]
            result = ((tot << np.uint64(23)) | (tot >> np.uint64(41))) + s[0]
            t = s[1] << np.uint64(17)
            s[2] ^= s[0]
            s[3] ^= s[1]
            s[1] ^= s[2]
            s[0] ^= s[3]
            s[2] ^= t
            s[3] = (s[3] << np.uint64(45)) | (s[3] >> np.uint64(19))
        return result

    def fill_buffer(self, num_bytes: int) -> bytes:
        n_vecs = (num_bytes + 8 * self.LANES - 1) // (8 * self.LANES)
        chunks = np.empty((n_vecs, self.LANES), dtype=np.uint64)
        for i in range(n_vecs):
            chunks[i] = self._next_vec()
        return chunks.tobytes()[:num_bytes]

    def next64_batch(self, n: int) -> np.ndarray:
        """Batch draws come from the N-lane vector stream (like
        fill_buffer); the scalar next64 intentionally uses its own
        single-lane stream, mirroring the reference's SIMD/scalar split."""
        n_vecs = (n + self.LANES - 1) // self.LANES
        chunks = np.empty((n_vecs, self.LANES), dtype=np.uint64)
        for i in range(n_vecs):
            chunks[i] = self._next_vec()
        return chunks.reshape(-1)[:n]


class RandAlgoGoldenPrime(RandAlgo):
    """'fast' tier: golden-prime multiplicative generator; weak randomness,
    reseeds from the strong generator every 256 KiB of generated data
    (reference: RandAlgoGoldenPrime.h:14-40)."""

    name = "fast"

    def __init__(self, seed: int | None = None):
        self._reseed_src = RandAlgoMT19937(seed)
        self._state = self._reseed_src.next64() | 1
        self._bytes_since_reseed = 0

    def next64(self) -> int:
        self._bytes_since_reseed += 8
        if self._bytes_since_reseed >= _GOLDEN_RESEED_BYTES:
            self._state = self._reseed_src.next64() | 1
            self._bytes_since_reseed = 0
        self._state = (self._state * _GOLDEN_PRIME) & _MASK64
        return _rotl(self._state, 32)

    _prime_powers: "np.ndarray | None" = None  # prime^(i+1), shared table

    def next64_batch(self, n: int) -> np.ndarray:
        """Closed-form batch: state_i = state0 * prime^i (mod 2^64), so a
        precomputed power table yields the EXACT scalar sequence in one
        vector multiply (reseed boundaries handled per sub-batch)."""
        cls = type(self)
        if cls._prime_powers is None:
            # sub-batches never exceed one reseed span (k <= trigger-1 <
            # _GOLDEN_RESEED_BYTES/8), so a fixed-size table built once
            # suffices — and being write-once, it is thread-safe to share
            size = _GOLDEN_RESEED_BYTES // 8
            powers = np.empty(size, dtype=np.uint64)
            acc = 1
            for i in range(size):
                acc = (acc * _GOLDEN_PRIME) & _MASK64
                powers[i] = acc
            cls._prime_powers = powers
        out = np.empty(n, dtype=np.uint64)
        filled = 0
        with np.errstate(over="ignore"):
            while filled < n:
                # scalar semantics: the call whose counter reaches the
                # limit reseeds first, draws from the NEW state and leaves
                # the counter at 0 — so from the current state we may draw
                # exactly (calls-until-trigger - 1) values
                trigger = (_GOLDEN_RESEED_BYTES
                           - self._bytes_since_reseed + 7) // 8
                if trigger <= 1:
                    out[filled] = self.next64()  # the reseeding call
                    filled += 1
                    continue
                k = min(n - filled, trigger - 1)
                states = np.uint64(self._state) * cls._prime_powers[:k]
                out[filled:filled + k] = \
                    (states << np.uint64(32)) | (states >> np.uint64(32))
                self._state = int(states[-1])
                self._bytes_since_reseed += 8 * k
                filled += k
        return out

    def fill_buffer(self, num_bytes: int) -> bytes:
        # next64_batch reseeds at the 256 KiB boundaries mid-stream, so
        # large buffers keep the exact scalar-stream (and reference
        # RandAlgoGoldenPrime) compressibility characteristics
        n = (num_bytes + 7) // 8
        return self.next64_batch(n).tobytes()[:num_bytes]


RAND_ALGO_NAMES = ("strong", "balanced_single", "balanced", "fast")


def create_rand_algo(name: str, seed: int | None = None) -> RandAlgo:
    """Factory, like RandAlgoSelectorTk::stringToAlgo."""
    table = {
        "strong": RandAlgoMT19937,
        "balanced_single": RandAlgoXoshiro256ss,
        "balanced": RandAlgoXoshiro256pp,
        "fast": RandAlgoGoldenPrime,
    }
    if name not in table:
        raise ValueError(f"unknown random algorithm: {name!r} "
                         f"(choose from {', '.join(RAND_ALGO_NAMES)})")
    return table[name](seed)
