"""Shared multipart-upload store for cross-worker MPU.

Reference: source/S3UploadStore.{h,cpp} — process-wide mutex-protected map
<bucket, object> -> {uploadID, completedParts, bytesDone}; emits the
completion signal when bytesDone reaches the object size; abort support for
interrupts (S3UploadStore.h:73-105). Used by --s3mpusharing style shared
uploads where multiple workers upload parts of one object.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field


@dataclass
class _UploadEntry:
    upload_id: str = ""
    completed_parts: "list[tuple[int, str]]" = field(default_factory=list)
    bytes_done: int = 0
    object_size: int = 0
    aborted: bool = False


class S3UploadStore:
    def __init__(self):
        self._lock = threading.Condition()
        self._uploads: "dict[tuple[str, str], _UploadEntry]" = {}

    def get_or_create_upload_id(self, bucket: str, key: str,
                                object_size: int, create_fn) -> str:
        """First caller wins the CreateMultipartUpload race and performs it;
        everyone else WAITS for that id (reference: one creator thread wins,
        S3UploadStore semantics) — two concurrent creates would split the
        parts across two uploads."""
        with self._lock:
            entry = self._uploads.get((bucket, key))
            if entry is None:
                entry = _UploadEntry(object_size=object_size)
                self._uploads[(bucket, key)] = entry
                creator = True
            else:
                creator = False
                while not entry.upload_id and not entry.aborted:
                    self._lock.wait(timeout=60)
                if entry.upload_id:
                    return entry.upload_id
                raise RuntimeError(
                    f"shared upload for {bucket}/{key} was aborted")
        try:
            upload_id = create_fn()
        except BaseException:
            with self._lock:
                entry.aborted = True
                self._lock.notify_all()
            raise
        with self._lock:
            entry.upload_id = upload_id
            self._lock.notify_all()
        return upload_id

    def add_completed_part(self, bucket: str, key: str, part_number: int,
                           etag: str, num_bytes: int) -> bool:
        """Record a finished part; returns True when this part completed the
        object (the caller then sends CompleteMultipartUpload)."""
        with self._lock:
            entry = self._uploads[(bucket, key)]
            entry.completed_parts.append((part_number, etag))
            entry.bytes_done += num_bytes
            return (entry.object_size > 0
                    and entry.bytes_done >= entry.object_size
                    and not entry.aborted)

    def get_completed_parts(self, bucket: str,
                            key: str) -> "list[tuple[int, str]]":
        with self._lock:
            return sorted(self._uploads[(bucket, key)].completed_parts)

    def mark_aborted(self, bucket: str, key: str) -> str:
        """Interrupt path: flag + return upload id for AbortMultipartUpload
        (reference: abort-MPU-on-interrupt, LocalWorker.cpp:6044-6135)."""
        with self._lock:
            entry = self._uploads.get((bucket, key))
            if entry is None:
                return ""
            entry.aborted = True
            self._lock.notify_all()  # wake waiters in get_or_create
            return entry.upload_id

    def pop_all_complete(self
                         ) -> "list[tuple[str, str, str, list]]":
        """(bucket, key, upload_id, sorted_parts) of every byte-complete
        upload; used by the separate MPUCOMPL phase."""
        with self._lock:
            out = []
            for (bucket, key), entry in list(self._uploads.items()):
                if entry.object_size and not entry.aborted \
                        and entry.bytes_done >= entry.object_size:
                    out.append((bucket, key, entry.upload_id,
                                sorted(entry.completed_parts)))
                    del self._uploads[(bucket, key)]
            return out

    def pop_all_unfinished(self) -> "list[tuple[str, str, str]]":
        """(bucket, key, upload_id) of every upload not yet completed."""
        with self._lock:
            out = []
            for (bucket, key), entry in self._uploads.items():
                if entry.object_size and \
                        entry.bytes_done < entry.object_size:
                    out.append((bucket, key, entry.upload_id))
            return out

    def clear(self) -> None:
        with self._lock:
            self._uploads.clear()


#: process-wide instance (one per service, like the reference's singleton)
shared_upload_store = S3UploadStore()
