"""S3/GCS object-storage client toolkit.

Reference: source/toolkits/S3Tk.{h,cpp} (AWS SDK based: global init,
per-worker client factory with endpoint round-robin by rank :167-316,
zero-copy memory streams) plus S3CredentialStore. Here the client is
self-contained stdlib HTTP + AWS Signature V4 (the public, documented
algorithm) — no SDK dependency, which also keeps GCS's S3-compat XML API
(interoperability mode) working unchanged.

Operations cover the phases in SURVEY.md section 2.2 "S3 mode": bucket
create/delete/head, object PUT/GET(+range)/HEAD/DELETE, ListObjectsV2,
multi-object delete, multipart create/uploadPart/complete/abort, and
object/bucket ACL + tagging get/put used by the metadata phases.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import http.client
import threading
import urllib.parse
import xml.etree.ElementTree as ET

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _xml_ns(root) -> str:
    """Namespace prefix of an XML root element ('' if unqualified)."""
    return root.tag[:root.tag.index("}") + 1] if root.tag.startswith("{") \
        else ""


def _build_tagging_xml(tags: "dict[str, str]") -> bytes:
    tagset = "".join(f"<Tag><Key>{k}</Key><Value>{v}</Value></Tag>"
                     for k, v in tags.items())
    return f"<Tagging><TagSet>{tagset}</TagSet></Tagging>".encode()


def _parse_tagging_xml(data: bytes) -> "dict[str, str]":
    root = ET.fromstring(data)
    ns = _xml_ns(root)
    return {tag.findtext(f"{ns}Key", ""): tag.findtext(f"{ns}Value", "")
            for tag in root.iter(f"{ns}Tag")}


class S3Error(Exception):
    def __init__(self, status: int, code: str, message: str):
        super().__init__(f"S3 error {status} {code}: {message}")
        self.status = status
        self.code = code


# -- ACL grants (reference: --s3aclgrantee/--s3aclgtype/--s3aclgrants) ------

_CANNED_ACLS = ("private", "public-read", "public-read-write",
                "authenticated-read")
_ACL_GRANT_HEADERS = {
    "read": "x-amz-grant-read",
    "write": "x-amz-grant-write",
    "racp": "x-amz-grant-read-acp",
    "wacp": "x-amz-grant-write-acp",
    "full": "x-amz-grant-full-control",
}
_ACL_GRANTEE_TYPE_KEYS = {"id": "id", "email": "emailAddress",
                          "uri": "uri", "group": "uri"}


def build_acl_headers(grantee: str, gtype: str, grants: str) -> "dict":
    """ACL request headers: canned x-amz-acl for special grantee values,
    x-amz-grant-* otherwise (reference: ProgArgs.h:286-297 value names)."""
    if not grantee:
        return {"x-amz-acl": "private"}
    if grantee in _CANNED_ACLS:
        return {"x-amz-acl": grantee}
    if "=" in grantee:  # inline form "id=..."/"emailAddress=..."/"uri=..."
        type_key, _, name = grantee.partition("=")
        value = f'{type_key}="{name}"'
    else:
        if gtype not in _ACL_GRANTEE_TYPE_KEYS:
            raise ValueError(
                "ACL grantee needs --s3aclgtype id|email|uri|group")
        value = f'{_ACL_GRANTEE_TYPE_KEYS[gtype]}="{grantee}"'
    headers = {}
    for perm in grants.split(","):
        perm = perm.strip().lower()
        if not perm or perm == "none":
            continue
        if perm not in _ACL_GRANT_HEADERS:
            raise ValueError(f"unknown ACL permission: {perm!r}")
        headers[_ACL_GRANT_HEADERS[perm]] = value
    if not headers:
        raise ValueError("ACL grantee given but no permissions "
                         "(--s3aclgrants)")
    return headers


# -- upload checksums (reference: --s3checksumalgo, x-amz-checksum-*) -------

_CRC32C_POLY = 0x82F63B78
_crc32c_table: "list[int]" = []
_native_crc32c = None


def _crc32c(data: bytes) -> int:
    """Castagnoli CRC32: native library when available (google-crc32c /
    crc32c), else a table-driven pure-python fallback (slow for multi-MiB
    blocks — fine for correctness, documented in --help)."""
    global _native_crc32c
    if _native_crc32c is None:
        try:
            import google_crc32c
            _native_crc32c = lambda b: int.from_bytes(  # noqa: E731
                google_crc32c.Checksum(b).digest(), "big")
        except ImportError:
            try:
                import crc32c as _c32c_mod
                _native_crc32c = _c32c_mod.crc32c
            except ImportError:
                _native_crc32c = False
    if _native_crc32c:
        return _native_crc32c(data)
    if not _crc32c_table:
        for i in range(256):
            crc = i
            for _ in range(8):
                crc = (crc >> 1) ^ _CRC32C_POLY if crc & 1 else crc >> 1
            _crc32c_table.append(crc)
    crc = 0xFFFFFFFF
    for byte in data:
        crc = _crc32c_table[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def build_checksum_headers(algo: str, body: bytes) -> "dict":
    """x-amz-sdk-checksum-algorithm + x-amz-checksum-<algo> (base64)."""
    import base64
    import zlib
    algo = algo.lower()
    if algo == "crc32":
        digest = zlib.crc32(body).to_bytes(4, "big")
    elif algo == "crc32c":
        digest = _crc32c(body).to_bytes(4, "big")
    elif algo == "sha1":
        digest = hashlib.sha1(body).digest()
    elif algo == "sha256":
        digest = hashlib.sha256(body).digest()
    else:
        raise ValueError(f"unknown checksum algorithm: {algo!r}")
    return {"x-amz-sdk-checksum-algorithm": algo.upper(),
            f"x-amz-checksum-{algo}": base64.b64encode(digest).decode()}


def retry_backoff_sleep(attempt: int, retry_notify=None) -> None:
    """The object clients' shared linear backoff (0.2s * attempt number)
    with the --ioretries audit hook: ONE definition so the per-retry
    accounting cannot silently diverge between the request, discard and
    resumable paths of the S3/GCS clients."""
    import time as _time
    delay = 0.2 * (attempt + 1)
    if retry_notify:
        retry_notify(delay)
    _time.sleep(delay)


def run_discard_with_retries(attempt_fn, num_retries: int,
                             retry_statuses, interrupt_check,
                             retry_notify=None) -> int:
    """Shared retry skeleton for streaming-discard downloads (used by the
    S3 and GCS clients): attempt_fn() -> (status, total_bytes). Retries
    connection errors and retryable statuses with linear backoff, checks
    for interruption between attempts, and raises the REAL final HTTP
    status on exhaustion instead of returning a zero byte count.
    retry_notify(slept_secs) feeds the worker's IoRetries audit."""
    last_err = None
    for attempt in range(num_retries + 1):
        if interrupt_check:
            interrupt_check()
        try:
            status, total = attempt_fn()
        except (OSError, http.client.HTTPException) as err:
            last_err = err
            if attempt < num_retries:
                retry_backoff_sleep(attempt, retry_notify)
            continue
        if status in retry_statuses:
            if attempt < num_retries:
                retry_backoff_sleep(attempt, retry_notify)
                continue
            raise S3Error(status, "RetryExhausted",
                          f"download failed with HTTP {status} after "
                          f"{attempt + 1} attempts")
        return total
    raise last_err if last_err is not None else S3Error(
        503, "RetryExhausted", "request retries exhausted")


class S3Client:
    """One S3 endpoint connection (per worker; endpoint picked round-robin
    by worker rank like the reference's client factory)."""

    def __init__(self, endpoint: str, access_key: str = "",
                 secret_key: str = "", region: str = "us-east-1",
                 virtual_hosted: bool = False, timeout: float = 60.0,
                 num_retries: int = 0, interrupt_check=None,
                 session_token: str = "", log_level: int = 0,
                 log_prefix: str = "s3_", unsigned_payload: bool = False,
                 retry_notify=None):
        parsed = urllib.parse.urlparse(
            endpoint if "//" in endpoint else "http://" + endpoint)
        self.scheme = parsed.scheme or "http"
        self.host = parsed.hostname or "localhost"
        self.port = parsed.port or (443 if self.scheme == "https" else 80)
        self.access_key = access_key
        self.secret_key = secret_key
        self.session_token = session_token
        self.region = region
        self.virtual_hosted = virtual_hosted
        self.timeout = timeout
        self.num_retries = num_retries
        self.interrupt_check = interrupt_check
        # retry_notify(slept_secs): per-retry hook feeding the worker's
        # IoRetries/IoRetryUsec audit counters (docs/fault-tolerance.md)
        self.retry_notify = retry_notify
        self.log_level = log_level
        self.log_prefix = log_prefix
        # --s3fastput / --s3sign 2: skip the per-request SHA256 of the
        # payload (the dominant client-side CPU cost of uploads)
        self.unsigned_payload = unsigned_payload
        self._log_fh = None
        self._log_lock = threading.Lock()  # shared-client (--s3single)
        # connections are PER THREAD (threading.local): one client
        # object can then be shared by every worker of a process
        # (--s3single, the reference's S3 client-singleton mode) with
        # each worker thread still driving its own connection — and the
        # default one-client-per-worker case is unchanged (one thread,
        # one connection). All conns are tracked for close().
        self._conn_local = threading.local()
        self._all_conns: "list[http.client.HTTPConnection]" = []
        self._conns_lock = threading.Lock()

    def _log_request(self, method: str, bucket: str, key: str,
                     status: int, num_bytes: int) -> None:
        """--s3log: per-request trace file <prefix>DATE.log (reference:
        --s3log/--s3logprefix SDK logging)."""
        if not self.log_level:
            return
        with self._log_lock:  # the client may be shared (--s3single)
            if self._log_fh is None:
                date = datetime.date.today().isoformat()
                self._log_fh = open(f"{self.log_prefix}{date}.log", "a")
            now = datetime.datetime.now().isoformat(timespec="milliseconds")
            self._log_fh.write(
                f"{now} {method} {self.host}:{self.port} /{bucket}/{key} "
                f"-> {status} ({num_bytes}B)\n")
            self._log_fh.flush()

    # -- low-level request --------------------------------------------------

    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._conn_local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self.scheme == "https"
                   else http.client.HTTPConnection)
            conn = cls(self.host, self.port, timeout=self.timeout)
            self._conn_local.conn = conn
            with self._conns_lock:
                self._all_conns.append(conn)
        return conn

    def _drop_connection(self) -> None:
        """Close and forget the calling thread's connection (retry path
        re-opens on next use)."""
        conn = getattr(self._conn_local, "conn", None)
        if conn is not None:
            conn.close()
            self._conn_local.conn = None
            with self._conns_lock:
                try:
                    self._all_conns.remove(conn)
                except ValueError:
                    pass

    def close(self) -> None:
        with self._conns_lock:
            conns, self._all_conns = self._all_conns, []
        for conn in conns:
            conn.close()
        self._conn_local = threading.local()
        if self._log_fh is not None:
            self._log_fh.close()
            self._log_fh = None

    @staticmethod
    def _encode_query(query: "dict[str, str]", sort: bool = False) -> str:
        """Percent-encode a query dict. The SAME encoding must serve the
        SigV4 canonical query (sorted) and the wire URL: quote_plus-style
        '+' for space would yield SignatureDoesNotMatch on servers that
        canonicalize the raw query string."""
        items = sorted(query.items()) if sort else query.items()
        return "&".join(
            f"{urllib.parse.quote(k, safe='')}"
            f"={urllib.parse.quote(str(v), safe='')}"
            for k, v in items)

    def _sign_v4(self, method: str, path: str, query: "dict[str, str]",
                 headers: "dict[str, str]", payload_hash: str) -> None:
        """AWS Signature Version 4 (public algorithm: canonical request ->
        string-to-sign -> HMAC chain)."""
        if not self.access_key:
            return
        now = datetime.datetime.now(datetime.timezone.utc)
        amz_date = now.strftime("%Y%m%dT%H%M%SZ")
        date_stamp = now.strftime("%Y%m%d")
        headers["x-amz-date"] = amz_date
        headers["x-amz-content-sha256"] = payload_hash
        if self.session_token:
            # temporary credentials: token is part of the signed headers
            headers["x-amz-security-token"] = self.session_token
        canon_query = self._encode_query(query, sort=True)
        signed_names = sorted(h.lower() for h in headers)
        canon_headers = "".join(
            f"{name}:{str(headers[next(h for h in headers if h.lower() == name)]).strip()}\n"
            for name in signed_names)
        signed_headers = ";".join(signed_names)
        canonical = "\n".join([method, path, canon_query, canon_headers,
                               signed_headers, payload_hash])
        scope = f"{date_stamp}/{self.region}/s3/aws4_request"
        string_to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amz_date, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

        def _hmac(key: bytes, msg: str) -> bytes:
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = _hmac(("AWS4" + self.secret_key).encode(), date_stamp)
        k = _hmac(k, self.region)
        k = _hmac(k, "s3")
        k = _hmac(k, "aws4_request")
        signature = hmac.new(k, string_to_sign.encode(),
                             hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={signed_headers}, Signature={signature}")

    _RETRY_STATUSES = (500, 502, 503, 429)

    def request(self, method: str, bucket: str = "", key: str = "",
                query: "dict | None" = None, body: bytes = b"",
                headers: "dict | None" = None,
                want_body: bool = True) -> "tuple[int, dict, bytes]":
        """One S3 request with transient-error retries at the request level
        (reference: S3InterruptibleRetryStrategy — retry whole requests on
        connection errors / retryable statuses, checking for interruption
        between attempts; accounting stays per successful request)."""
        last_err = None
        for attempt in range(self.num_retries + 1):
            if self.interrupt_check:
                self.interrupt_check()
            try:
                status, resp_headers, data = self._request_once(
                    method, bucket, key, query, body, headers, want_body)
            except (OSError, http.client.HTTPException) as err:
                # covers dropped connections too (IncompleteRead etc.)
                last_err = err
                if attempt < self.num_retries:
                    retry_backoff_sleep(attempt, self.retry_notify)
                continue
            self._log_request(method, bucket, key, status,
                              len(body) if body else len(data))
            if status in self._RETRY_STATUSES and attempt < self.num_retries:
                retry_backoff_sleep(attempt, self.retry_notify)
                continue
            return status, resp_headers, data
        raise last_err if last_err is not None else S3Error(
            503, "RetryExhausted", "request retries exhausted")

    def _request_once(self, method: str, bucket: str = "", key: str = "",
                      query: "dict | None" = None, body: bytes = b"",
                      headers: "dict | None" = None,
                      want_body: bool = True) -> "tuple[int, dict, bytes]":
        query = {k: str(v) for k, v in (query or {}).items()}
        headers = dict(headers or {})
        if self.virtual_hosted and bucket:
            host = f"{bucket}.{self.host}"
            path = "/" + urllib.parse.quote(key) if key else "/"
        else:
            host = self.host
            path = "/" + bucket + ("/" + urllib.parse.quote(key)
                                   if key else "")
            if not bucket:
                path = "/"
        headers["Host"] = host if self.port in (80, 443) \
            else f"{host}:{self.port}"
        if self.unsigned_payload and body:
            payload_hash = "UNSIGNED-PAYLOAD"
        else:
            payload_hash = hashlib.sha256(body).hexdigest() if body \
                else _EMPTY_SHA256
        self._sign_v4(method, path, query, headers, payload_hash)
        url = path
        if query:
            url += "?" + self._encode_query(query)
        conn = self._connection()
        try:
            conn.request(method, url, body=body or None, headers=headers)
            resp = conn.getresponse()
            data = resp.read() if want_body or resp.status >= 300 else b""
            if not want_body and resp.status < 300:
                resp.read()  # drain for keep-alive
            return resp.status, dict(resp.getheaders()), data
        except (http.client.HTTPException, OSError):
            self._drop_connection()  # broken keep-alive: this thread's only
            raise

    def _check(self, status: int, data: bytes, ok=(200, 204)) -> None:
        if status in ok:
            return
        code, message = "Unknown", data.decode(errors="replace")[:300]
        try:
            root = ET.fromstring(data)
            code = root.findtext("Code", default=code)
            message = root.findtext("Message", default=message)
        except ET.ParseError:
            pass
        raise S3Error(status, code, message)

    # -- bucket ops ----------------------------------------------------------

    def create_bucket(self, bucket: str) -> None:
        status, _, data = self.request("PUT", bucket)
        if status == 409:  # BucketAlreadyOwnedByYou: treat as success
            return
        self._check(status, data, ok=(200,))

    def delete_bucket(self, bucket: str) -> None:
        status, _, data = self.request("DELETE", bucket)
        self._check(status, data)

    def head_bucket(self, bucket: str) -> bool:
        status, _, _ = self.request("HEAD", bucket)
        return status == 200

    # -- object ops ----------------------------------------------------------

    def put_object(self, bucket: str, key: str, body: bytes,
                   extra_headers: "dict | None" = None) -> None:
        status, _, data = self.request("PUT", bucket, key, body=body,
                                       headers=extra_headers)
        self._check(status, data, ok=(200,))

    def get_object(self, bucket: str, key: str,
                   range_start: "int | None" = None,
                   range_len: "int | None" = None,
                   extra_headers: "dict | None" = None) -> bytes:
        headers = dict(extra_headers or {})
        if range_start is not None:
            end = "" if range_len is None else str(range_start + range_len - 1)
            headers["Range"] = f"bytes={range_start}-{end}"
        status, _, data = self.request("GET", bucket, key, headers=headers)
        if status not in (200, 206):
            self._check(status, data, ok=())
        return data

    def get_object_discard(self, bucket: str, key: str,
                           range_start: "int | None" = None,
                           range_len: "int | None" = None,
                           extra_headers: "dict | None" = None) -> int:
        """--s3fastget: stream the body in chunks and drop it, returning
        only the byte count (reference: useS3FastRead sends downloads to
        /dev/null instead of a memory buffer). Same transient-error retry
        and interrupt semantics as request()."""
        return run_discard_with_retries(
            lambda: self._get_discard_once(bucket, key, range_start,
                                           range_len, extra_headers),
            self.num_retries, self._RETRY_STATUSES, self.interrupt_check,
            retry_notify=self.retry_notify)

    def _get_discard_once(self, bucket, key, range_start, range_len,
                          extra_headers) -> "tuple[int, int]":
        headers = dict(extra_headers or {})
        if range_start is not None:
            end = "" if range_len is None else str(range_start + range_len - 1)
            headers["Range"] = f"bytes={range_start}-{end}"
        if self.virtual_hosted and bucket:
            host = f"{bucket}.{self.host}"
            path = "/" + urllib.parse.quote(key) if key else "/"
        else:
            host = self.host
            path = f"/{bucket}/" + urllib.parse.quote(key)
        headers["Host"] = host if self.port in (80, 443) \
            else f"{host}:{self.port}"
        self._sign_v4("GET", path, {}, headers, _EMPTY_SHA256)
        conn = self._connection()
        try:
            conn.request("GET", path, headers=headers)
            resp = conn.getresponse()
            if resp.status in self._RETRY_STATUSES:
                resp.read()  # drain for keep-alive
                return resp.status, 0
            if resp.status not in (200, 206):
                self._check(resp.status, resp.read(), ok=())
            total = 0
            chunks = 0
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    break
                total += len(chunk)
                chunks += 1
                if self.interrupt_check and chunks % 16 == 0:
                    self.interrupt_check()  # long streams stay abortable
            self._log_request("GET", bucket, key, resp.status, total)
            return resp.status, total
        except (http.client.HTTPException, OSError):
            self._drop_connection()
            raise

    def head_object(self, bucket: str, key: str,
                    extra_headers: "dict | None" = None) -> "dict[str, str]":
        status, headers, _ = self.request("HEAD", bucket, key,
                                          headers=extra_headers)
        if status != 200:
            raise S3Error(status, "NotFound", key)
        return headers

    def delete_object(self, bucket: str, key: str) -> None:
        status, _, data = self.request("DELETE", bucket, key)
        self._check(status, data)

    def delete_objects(self, bucket: str, keys: "list[str]") -> None:
        """Multi-object delete (reference: --s3multidel). With Quiet mode
        the 200 reply body lists only per-key failures — surface them."""
        objs = "".join(f"<Object><Key>{k}</Key></Object>" for k in keys)
        body = (f"<Delete><Quiet>true</Quiet>{objs}</Delete>").encode()
        status, _, data = self.request("POST", bucket, query={"delete": ""},
                                       body=body)
        self._check(status, data, ok=(200,))
        try:
            root = ET.fromstring(data)
        except ET.ParseError:
            return
        ns = _xml_ns(root)
        errors = [(el.findtext(f"{ns}Key", ""), el.findtext(f"{ns}Code", ""))
                  for el in root.iter(f"{ns}Error")]
        if errors:
            key, code = errors[0]
            raise S3Error(200, code or "MultiDeleteError",
                          f"{len(errors)} object(s) failed to delete, "
                          f"first: {key}")

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000,
                     continuation_token: str = ""
                     ) -> "tuple[list[str], str]":
        """ListObjectsV2 page -> (keys, next_continuation_token)."""
        entries, next_token = self.list_objects_entries(
            bucket, prefix, max_keys, continuation_token)
        return [k for k, _size in entries], next_token

    def list_objects_entries(self, bucket: str, prefix: str = "",
                             max_keys: int = 1000,
                             continuation_token: str = ""
                             ) -> "tuple[list[tuple[str, int]], str]":
        """ListObjectsV2 page -> ([(key, size)], next_continuation_token).
        The sized variant feeds the bucket treescan's "f <size> <name>"
        treefile lines (reference: S3Tk::scanCustomTree, S3Tk.cpp:330+)."""
        query = {"list-type": "2", "max-keys": str(max_keys)}
        if prefix:
            query["prefix"] = prefix
        if continuation_token:
            query["continuation-token"] = continuation_token
        status, _, data = self.request("GET", bucket, query=query)
        self._check(status, data, ok=(200,))
        root = ET.fromstring(data)
        ns = _xml_ns(root)
        entries = []
        for el in root.findall(f"{ns}Contents"):
            key = el.findtext(f"{ns}Key")
            if key:
                entries.append(
                    (key, int(el.findtext(f"{ns}Size", default="0") or 0)))
        next_token = root.findtext(f"{ns}NextContinuationToken", default="")
        return entries, next_token

    # -- multipart ------------------------------------------------------------

    def create_multipart_upload(self, bucket: str, key: str,
                                extra_headers: "dict | None" = None) -> str:
        status, _, data = self.request("POST", bucket, key,
                                       query={"uploads": ""},
                                       headers=extra_headers)
        self._check(status, data, ok=(200,))
        root = ET.fromstring(data)
        ns = _xml_ns(root)
        upload_id = root.findtext(f"{ns}UploadId")
        if not upload_id:
            raise S3Error(500, "NoUploadId", "missing UploadId in reply")
        return upload_id

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_number: int, body: bytes,
                    extra_headers: "dict | None" = None) -> str:
        status, headers, data = self.request(
            "PUT", bucket, key,
            query={"partNumber": str(part_number), "uploadId": upload_id},
            body=body, headers=extra_headers)
        self._check(status, data, ok=(200,))
        return headers.get("ETag", headers.get("etag", ""))

    #: --s3checksumalgo algo -> CompleteMultipartUpload per-part element
    CHECKSUM_XML_TAGS = {"crc32": "ChecksumCRC32", "crc32c": "ChecksumCRC32C",
                         "sha1": "ChecksumSHA1", "sha256": "ChecksumSHA256"}

    def complete_multipart_upload(self, bucket: str, key: str,
                                  upload_id: str, parts,
                                  checksum_algo: str = "") -> None:
        """parts: (part_number, etag) tuples, or (part_number, etag,
        checksum_b64) when the parts were uploaded with x-amz-checksum-*
        headers — S3 then REQUIRES the per-part checksum in the completion
        XML."""
        tag = self.CHECKSUM_XML_TAGS.get(checksum_algo.lower(), "")
        parts_xml = "".join(
            f"<Part><PartNumber>{p[0]}</PartNumber><ETag>{p[1]}</ETag>"
            + (f"<{tag}>{p[2]}</{tag}>" if tag and len(p) > 2 else "")
            + "</Part>"
            for p in sorted(parts))
        body = (f"<CompleteMultipartUpload>{parts_xml}"
                f"</CompleteMultipartUpload>").encode()
        status, _, data = self.request("POST", bucket, key,
                                       query={"uploadId": upload_id},
                                       body=body)
        self._check(status, data, ok=(200,))

    def abort_multipart_upload(self, bucket: str, key: str,
                               upload_id: str) -> None:
        status, _, data = self.request("DELETE", bucket, key,
                                       query={"uploadId": upload_id})
        self._check(status, data)

    def list_multipart_uploads(self, bucket: str, prefix: str = "",
                               key_marker: str = "",
                               upload_id_marker: str = ""
                               ) -> "tuple[list[tuple[str, str]], str, str]":
        """ListMultipartUploads page -> ([(key, upload_id)...],
        next_key_marker, next_upload_id_marker); empty markers = done."""
        query = {"uploads": ""}
        if prefix:
            query["prefix"] = prefix
        if key_marker:
            query["key-marker"] = key_marker
        if upload_id_marker:
            query["upload-id-marker"] = upload_id_marker
        status, _, data = self.request("GET", bucket, query=query)
        self._check(status, data, ok=(200,))
        root = ET.fromstring(data)
        ns = _xml_ns(root)
        uploads = [(el.findtext(f"{ns}Key", default=""),
                    el.findtext(f"{ns}UploadId", default=""))
                   for el in root.findall(f"{ns}Upload")]
        truncated = root.findtext(f"{ns}IsTruncated", default="false")
        if truncated.lower() == "true":
            return (uploads, root.findtext(f"{ns}NextKeyMarker", default=""),
                    root.findtext(f"{ns}NextUploadIdMarker", default=""))
        return uploads, "", ""

    # -- metadata ops (ACL / tagging) ----------------------------------------

    def put_object_tagging(self, bucket: str, key: str,
                           tags: "dict[str, str]") -> None:
        status, _, data = self.request("PUT", bucket, key,
                                       query={"tagging": ""},
                                       body=_build_tagging_xml(tags))
        self._check(status, data, ok=(200,))

    def get_object_tagging(self, bucket: str, key: str) -> "dict[str, str]":
        status, _, data = self.request("GET", bucket, key,
                                       query={"tagging": ""})
        self._check(status, data, ok=(200,))
        return _parse_tagging_xml(data)

    def put_object_acl(self, bucket: str, key: str, acl: str = "",
                       acl_headers: "dict | None" = None) -> None:
        status, _, data = self.request(
            "PUT", bucket, key, query={"acl": ""},
            headers=acl_headers if acl_headers else {"x-amz-acl": acl})
        self._check(status, data, ok=(200,))

    def get_object_acl(self, bucket: str, key: str) -> bytes:
        status, _, data = self.request("GET", bucket, key,
                                       query={"acl": ""})
        self._check(status, data, ok=(200,))
        return data

    def delete_object_tagging(self, bucket: str, key: str) -> None:
        status, _, data = self.request("DELETE", bucket, key,
                                       query={"tagging": ""})
        self._check(status, data)

    def put_bucket_tagging(self, bucket: str,
                           tags: "dict[str, str]") -> None:
        status, _, data = self.request("PUT", bucket,
                                       query={"tagging": ""},
                                       body=_build_tagging_xml(tags))
        self._check(status, data, ok=(200, 204))

    def get_bucket_tagging(self, bucket: str) -> "dict[str, str]":
        status, _, data = self.request("GET", bucket,
                                       query={"tagging": ""})
        self._check(status, data, ok=(200,))
        return _parse_tagging_xml(data)

    def delete_bucket_tagging(self, bucket: str) -> None:
        status, _, data = self.request("DELETE", bucket,
                                       query={"tagging": ""})
        self._check(status, data)

    def put_bucket_versioning(self, bucket: str, enabled: bool) -> None:
        state = "Enabled" if enabled else "Suspended"
        body = (f"<VersioningConfiguration><Status>{state}</Status>"
                f"</VersioningConfiguration>").encode()
        status, _, data = self.request("PUT", bucket,
                                       query={"versioning": ""}, body=body)
        self._check(status, data, ok=(200,))

    def get_bucket_versioning(self, bucket: str) -> str:
        status, _, data = self.request("GET", bucket,
                                       query={"versioning": ""})
        self._check(status, data, ok=(200,))
        root = ET.fromstring(data)
        ns = _xml_ns(root)
        return root.findtext(f"{ns}Status", default="")

    def put_object_lock_configuration(self, bucket: str,
                                      mode: str = "GOVERNANCE",
                                      days: int = 1) -> None:
        """Empty mode clears the default-retention rule (cleanup path)."""
        rule = (f"<Rule><DefaultRetention><Mode>{mode}</Mode>"
                f"<Days>{days}</Days></DefaultRetention></Rule>"
                if mode else "")
        body = (f"<ObjectLockConfiguration>"
                f"<ObjectLockEnabled>Enabled</ObjectLockEnabled>{rule}"
                f"</ObjectLockConfiguration>").encode()
        status, _, data = self.request("PUT", bucket,
                                       query={"object-lock": ""}, body=body)
        self._check(status, data, ok=(200,))

    def get_object_lock_configuration(self, bucket: str) -> str:
        status, _, data = self.request("GET", bucket,
                                       query={"object-lock": ""})
        self._check(status, data, ok=(200,))
        root = ET.fromstring(data)
        ns = _xml_ns(root)
        rule = root.find(f"{ns}Rule/{ns}DefaultRetention/{ns}Mode")
        return rule.text if rule is not None else ""

    def put_bucket_acl(self, bucket: str, acl: str = "",
                       acl_headers: "dict | None" = None) -> None:
        status, _, data = self.request(
            "PUT", bucket, query={"acl": ""},
            headers=acl_headers if acl_headers else {"x-amz-acl": acl})
        self._check(status, data, ok=(200,))

    def get_bucket_acl(self, bucket: str) -> bytes:
        status, _, data = self.request("GET", bucket, query={"acl": ""})
        self._check(status, data, ok=(200,))
        return data


class S3CredentialStore:
    """Multi-credential round-robin (reference: S3CredentialStore, 234 LoC
    — spreads workers over credential pairs for per-user rate limits).
    Parsed once per (file, list) source and shared by all workers."""

    _cache: "dict[tuple, S3CredentialStore]" = {}

    def __init__(self, cred_file: str, cred_list: str,
                 fallback: "tuple[str, str]"):
        self.pairs: "list[tuple[str, str]]" = []
        if cred_file:
            with open(cred_file) as f:
                for line in f:
                    line = line.strip()
                    if line and not line.startswith("#"):
                        key, _, secret = line.partition(":")
                        self.pairs.append((key, secret))
        for item in (cred_list or "").split(","):
            item = item.strip()
            if item:
                key, _, secret = item.partition(":")
                self.pairs.append((key, secret))
        if not self.pairs:
            self.pairs = [fallback]

    @classmethod
    def for_config(cls, cfg) -> "S3CredentialStore":
        cache_key = (cfg.s3_cred_file_path, cfg.s3_cred_list,
                     cfg.s3_access_key, cfg.s3_secret_key)
        store = cls._cache.get(cache_key)
        if store is None:
            store = cls(cfg.s3_cred_file_path, cfg.s3_cred_list,
                        (cfg.s3_access_key, cfg.s3_secret_key))
            cls._cache[cache_key] = store
        return store

    def for_rank(self, rank: int) -> "tuple[str, str]":
        return self.pairs[rank % len(self.pairs)]


def make_client_for_rank(cfg, rank: int, interrupt_check=None,
                         retry_notify=None) -> S3Client:
    """Endpoint + credential round-robin by worker rank
    (reference: S3Tk.cpp:167-316 + S3CredentialStore). With the GCS-native
    backend (gs:// paths) this returns a `gcs_tk.GcsClient` instead — the
    method surface is identical, so callers stay backend-agnostic.

    Request-level retries take the LARGER of --s3retries and --ioretries
    (the object transport is the data plane here), and every retry is
    reported through retry_notify into the worker's IoRetries audit."""
    num_retries = max(cfg.s3_num_retries,
                      getattr(cfg, "io_num_retries", 0))
    if getattr(cfg, "object_backend", "") == "gcs":
        from .gcs_tk import (GCS_DEFAULT_ENDPOINT, GcsClient,
                             GcsTokenProvider)
        endpoints = [e.strip() for e in cfg.gcs_endpoint_str.split(",")
                     if e.strip()] or [GCS_DEFAULT_ENDPOINT]
        return GcsClient(
            endpoints[rank % len(endpoints)], project=cfg.gcs_project,
            token_provider=GcsTokenProvider.for_config(cfg),
            num_retries=num_retries, interrupt_check=interrupt_check,
            resumable=getattr(cfg, "gcs_resumable", False),
            retry_notify=retry_notify)
    endpoints = [e.strip() for e in cfg.s3_endpoints_str.split(",")
                 if e.strip()]
    if not endpoints:
        raise ValueError("no S3 endpoints configured (--s3endpoints)")
    endpoint = endpoints[rank % len(endpoints)]
    access_key, secret_key = S3CredentialStore.for_config(cfg).for_rank(rank)
    return S3Client(endpoint, access_key=access_key,
                    secret_key=secret_key, region=cfg.s3_region,
                    virtual_hosted=cfg.s3_virtual_hosted,
                    num_retries=num_retries,
                    interrupt_check=interrupt_check,
                    session_token=cfg.s3_session_token,
                    log_level=cfg.s3_log_level,
                    log_prefix=cfg.s3_log_prefix,
                    unsigned_payload=(cfg.s3_fast_put
                                      or cfg.s3_sign_policy == 2),
                    retry_notify=retry_notify)


