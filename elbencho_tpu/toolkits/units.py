"""Unit parsing/formatting (reference: source/toolkits/UnitTk.{h,cpp}).

Parses human size strings ("4K", "1M", "10g", "1GiB", "2TB") to bytes and
formats byte counts back to short human units. Like the reference, bare
suffixes K/M/G/T/P/E are base-2 (KiB etc.); explicit "KB"/"kB" decimal forms
are base-10; "KiB" forms are base-2.
"""

from __future__ import annotations

_BASE2 = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40,
          "p": 1 << 50, "e": 1 << 60}
_BASE10 = {"k": 10 ** 3, "m": 10 ** 6, "g": 10 ** 9, "t": 10 ** 12,
           "p": 10 ** 15, "e": 10 ** 18}

_SUFFIX_ORDER = ["", "K", "M", "G", "T", "P", "E"]


class UnitParseError(ValueError):
    pass


def parse_size(value: "str | int | None") -> int:
    """Parse a human size string to a byte count.

    Accepts: plain ints; "<num>" ; "<num>K" (base-2); "<num>KiB" (base-2);
    "<num>KB" (base-10). Case-insensitive. Floats allowed with suffix
    ("1.5G").
    """
    if value is None:
        return 0
    if isinstance(value, (int, float)):
        return int(value)
    s = str(value).strip()
    if not s:
        return 0
    low = s.lower()
    num_end = 0
    while num_end < len(low) and (low[num_end].isdigit() or low[num_end] in "."):
        num_end += 1
    num_str, suffix = low[:num_end], low[num_end:].strip()
    if not num_str:
        raise UnitParseError(f"no numeric part in size string: {value!r}")
    num = float(num_str) if "." in num_str else int(num_str)
    if not suffix:
        return int(num)
    mult_map = _BASE2
    if suffix.endswith("ib"):  # KiB/MiB/...
        suffix = suffix[:-2]
        mult_map = _BASE2
    elif suffix.endswith("b"):  # KB/MB/... => base-10; bare "b" = bytes
        suffix = suffix[:-1]
        mult_map = _BASE10
        if not suffix:
            return int(num)
    if suffix not in mult_map:
        raise UnitParseError(f"unknown size suffix in {value!r}")
    return int(num * mult_map[suffix])


def format_bytes(num_bytes: float, base10: bool = False, precision: int = 1) -> str:
    """Format a byte count with short base-2 unit ("4K", "1.5M", "10G")."""
    base = 1000.0 if base10 else 1024.0
    num = float(num_bytes)
    for suffix in _SUFFIX_ORDER:
        if abs(num) < base or suffix == _SUFFIX_ORDER[-1]:
            if num == int(num):
                return f"{int(num)}{suffix}"
            return f"{num:.{precision}f}{suffix}"
        num /= base
    return f"{num_bytes}"


def format_duration_secs(secs: float) -> str:
    """"1h:40m:13s"-style duration formatting (storage_sweep convention)."""
    secs = int(secs)
    h, rem = divmod(secs, 3600)
    m, s = divmod(rem, 60)
    if h:
        return f"{h}h:{m:02d}m:{s:02d}s"
    if m:
        return f"{m}m:{s:02d}s"
    return f"{s}s"


def parse_uint_list(value: str) -> "list[int]":
    """Parse comma-separated integer list ("0,1,2")."""
    if not value:
        return []
    return [int(part) for part in str(value).split(",") if part.strip() != ""]
