"""Leveled logger with static, thread-safe error history.

Reference: source/Logger.{h,cpp} — level-filtered timestamped console
streams plus a process-wide error history that service instances replay to
the master over the prep protocol (XFER_PREP_ERRORHISTORY; Logger.h:33-161).
"""

from __future__ import annotations

import sys
import threading
import time

LOG_NORMAL = 0
LOG_VERBOSE = 1
LOG_DEBUG = 2

_LEVEL_NAMES = {LOG_NORMAL: "NORMAL", LOG_VERBOSE: "VERBOSE", LOG_DEBUG: "DEBUG"}

_lock = threading.Lock()
_log_level = LOG_NORMAL
_error_history: "list[str]" = []
_error_history_enabled = False


def set_log_level(level: int) -> None:
    global _log_level
    _log_level = int(level)


def get_log_level() -> int:
    return _log_level


def enable_error_history(enabled: bool = True) -> None:
    """Services keep error history for replay to the master."""
    global _error_history_enabled
    _error_history_enabled = enabled


def log(level: int, message: str, *, stream=None) -> None:
    if level > _log_level:
        return
    ts = time.strftime("%Y-%m-%d %H:%M:%S")
    out = stream or sys.stdout
    with _lock:
        print(f"{ts} {message}", file=out, flush=True)


def log_error(message: str) -> None:
    ts = time.strftime("%Y-%m-%d %H:%M:%S")
    line = f"{ts} ERROR: {message}"
    with _lock:
        print(line, file=sys.stderr, flush=True)
        if _error_history_enabled:
            _error_history.append(line)


def get_error_history() -> "list[str]":
    with _lock:
        return list(_error_history)


def clear_error_history() -> None:
    with _lock:
        _error_history.clear()
