"""File toolkit: locking, sparse detection, tree scanning.

Reference: source/toolkits/FileTk.{h,cpp} (586 LoC) — flock range/full
templates (FileTk.h:50+, --flock), sparse/compressed file detection,
bottom-up mkdirat, and the directory-tree scan behind --treescan /
elbencho-scan-path.
"""

from __future__ import annotations

import base64
import fcntl
import os

from .path_store import (TREEFILE_BASE64_HEADER, PathStore)


class FileRangeLock:
    """POSIX advisory byte-range lock around one I/O op (reference:
    FileTk flock templates; --flock range|full)."""

    def __init__(self, fd: int, mode: str, offset: int, length: int,
                 is_write: bool):
        self.fd = fd
        self.is_write = is_write
        if mode == "full":
            self.offset, self.length = 0, 0  # 0 length = whole file
        else:
            self.offset, self.length = offset, length

    def __enter__(self):
        fcntl.lockf(self.fd, fcntl.LOCK_EX if self.is_write
                    else fcntl.LOCK_SH, self.length, self.offset, 0)
        return self

    def __exit__(self, *exc):
        fcntl.lockf(self.fd, fcntl.LOCK_UN, self.length, self.offset, 0)
        return False


def file_is_sparse_or_compressed(path: str) -> bool:
    """st_blocks*512 < st_size => holes or FS compression
    (reference: FileTk sparse detection)."""
    st = os.stat(path)
    return (st.st_blocks * 512) < st.st_size


def scan_tree(root: str) -> "tuple[PathStore, PathStore, bool]":
    """Walk a real directory tree into (dirs_store, files_store,
    needs_base64). Used by --treescan / elbencho-tpu-scan-path
    (reference: FileTk dir-tree scan + tools/elbencho-scan-path)."""
    dirs = PathStore()
    files = PathStore()
    needs_b64 = False
    root = root.rstrip("/")
    for dirpath, dirnames, filenames in os.walk(root):
        rel_dir = os.path.relpath(dirpath, root)
        if rel_dir != ".":
            dirs.load_dirs_from_text(f"d {rel_dir}")
            if "\n" in rel_dir:
                needs_b64 = True
        for name in filenames:
            full = os.path.join(dirpath, name)
            rel = os.path.relpath(full, root)
            try:
                size = os.stat(full).st_size
            except OSError:
                continue
            if "\n" in rel:
                needs_b64 = True
            files.load_files_from_text(f"f {size} {rel}")
    return dirs, files, needs_b64


def write_treefile(out_path: str, dirs: PathStore, files: PathStore,
                   use_base64: bool = False) -> None:
    with open(out_path, "w", encoding="utf-8",
              errors="surrogateescape") as f:
        if use_base64:
            f.write(TREEFILE_BASE64_HEADER + "\n")

            def enc(s: str) -> str:
                return base64.b64encode(
                    s.encode("utf-8", errors="surrogateescape")).decode()
        else:
            def enc(s: str) -> str:
                return s
        for elem in dirs.elems:
            f.write(f"d {enc(elem.path)}\n")
        for elem in files.elems:
            f.write(f"f {elem.total_len} {enc(elem.path)}\n")


def makedirs_bottom_up(path: str, mode: int = 0o755) -> None:
    """Reference: FileTk bottom-up mkdirat — try the leaf first, walk up
    only on ENOENT (cheaper for mostly-existing deep trees)."""
    try:
        os.mkdir(path, mode)
        return
    except FileExistsError:
        return
    except FileNotFoundError:
        parent = os.path.dirname(path)
        if parent and parent != path:
            makedirs_bottom_up(parent, mode)
            os.makedirs(path, mode, exist_ok=True)
