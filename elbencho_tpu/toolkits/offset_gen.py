"""Offset generators for the block I/O hot loops.

Reference: source/toolkits/offsetgen/OffsetGenerator.h (Sequential :48,
ReverseSeq :106, Random :185, RandomAligned :252, Strided :323) and
OffsetGenRandomAlignedFullCoverageV2.h (LCG permutation over block indices,
power-of-2 modulus — the default for aligned random *writes* so every block
is hit exactly once; LocalWorker.cpp:1177-1184).

Interface: each generator yields (offset, length) pairs via next_block();
returns None when the configured amount of bytes has been generated.
"""

from __future__ import annotations

import numpy as np

from .random_algos import RandAlgo


class OffsetGenerator:
    def next_block(self) -> "tuple[int, int] | None":
        raise NotImplementedError

    def reset(self) -> None:
        raise NotImplementedError

    def __iter__(self):
        while True:
            blk = self.next_block()
            if blk is None:
                return
            yield blk

    def next_batch(self, max_n: int):
        """Up to max_n blocks as (offsets, lengths) uint64 numpy arrays,
        or None when exhausted. The deterministic generators override this
        with closed-form array math so the native C++ loop is fed without
        per-block Python iteration; the PRNG-driven ones fall back to this
        loop (their sequence must match the scalar path exactly)."""
        offs = np.empty(max_n, dtype=np.uint64)
        lens = np.empty(max_n, dtype=np.uint64)
        i = 0
        while i < max_n:
            blk = self.next_block()
            if blk is None:
                break
            offs[i] = blk[0]
            lens[i] = blk[1]
            i += 1
        if i == 0:
            return None
        return offs[:i], lens[:i]

    @staticmethod
    def _batch_lens(max_n: int, remaining: int, block_size: int):
        """Shared batch sizing: k full blocks except a short final one
        when remaining isn't block-divisible -> (k, lengths array)."""
        k = min(max_n, (remaining + block_size - 1) // block_size)
        lens = np.full(k, block_size, dtype=np.uint64)
        if k * block_size > remaining:  # short final block
            lens[-1] = remaining - (k - 1) * block_size
        return k, lens

    @classmethod
    def _batch_arrays(cls, max_n: int, remaining: int, block_size: int,
                      first_off: int, step: int):
        """Closed-form batch for arithmetic progressions: k offsets
        first_off + i*step with the shared length sizing."""
        k, lens = cls._batch_lens(max_n, remaining, block_size)
        offs = (np.uint64(first_off)
                + np.arange(k, dtype=np.uint64) * np.uint64(step))
        return offs, lens, k


class OffsetGenSequential(OffsetGenerator):
    """Forward sequential over [start, start+num_bytes); final block may be
    short (reference: OffsetGenerator.h:48-104)."""

    def __init__(self, num_bytes: int, block_size: int, start: int = 0):
        if block_size <= 0:
            raise ValueError("block_size must be > 0")
        self.num_bytes = num_bytes
        self.block_size = block_size
        self.start = start
        self.reset()

    def reset(self) -> None:
        self._pos = 0

    def next_block(self):
        if self._pos >= self.num_bytes:
            return None
        length = min(self.block_size, self.num_bytes - self._pos)
        off = self.start + self._pos
        self._pos += length
        return (off, length)

    def next_batch(self, max_n: int):
        if self._pos >= self.num_bytes:
            return None
        offs, lens, _ = self._batch_arrays(
            max_n, self.num_bytes - self._pos, self.block_size,
            self.start + self._pos, self.block_size)
        self._pos += int(lens.sum())
        return offs, lens


class OffsetGenReverseSeq(OffsetGenerator):
    """Backward sequential (``--backward``): last block first
    (reference: OffsetGenerator.h:106-183)."""

    def __init__(self, num_bytes: int, block_size: int, start: int = 0):
        if block_size <= 0:
            raise ValueError("block_size must be > 0")
        self.num_bytes = num_bytes
        self.block_size = block_size
        self.start = start
        self.reset()

    def reset(self) -> None:
        self._bytes_left = self.num_bytes
        # first (i.e. last-in-file) block absorbs the remainder
        rem = self.num_bytes % self.block_size
        self._next_len = rem if rem else min(self.block_size, self.num_bytes)

    def next_block(self):
        if self._bytes_left <= 0:
            return None
        length = self._next_len
        self._bytes_left -= length
        off = self.start + self._bytes_left
        self._next_len = min(self.block_size, self._bytes_left)
        return (off, length)


class OffsetGenRandom(OffsetGenerator):
    """Unaligned uniform-random offsets; generates ``num_bytes`` total over a
    range of ``range_len`` bytes (reference: OffsetGenerator.h:185-250)."""

    def __init__(self, rand: RandAlgo, num_bytes: int, block_size: int,
                 range_len: int, start: int = 0):
        if block_size <= 0:
            raise ValueError("block_size must be > 0")
        if range_len < block_size:
            raise ValueError("range smaller than block size")
        self.rand = rand
        self.num_bytes = num_bytes
        self.block_size = block_size
        self.range_len = range_len
        self.start = start
        self.reset()

    def reset(self) -> None:
        self._bytes_left = self.num_bytes

    def next_block(self):
        if self._bytes_left <= 0:
            return None
        length = min(self.block_size, self._bytes_left)
        max_off = self.range_len - length
        off = self.start + (self.rand.next64() % (max_off + 1) if max_off else 0)
        self._bytes_left -= length
        return (off, length)

    def next_batch(self, max_n: int):
        if self._bytes_left <= 0:
            return None
        bs = self.block_size
        k, lens = self._batch_lens(max_n, self._bytes_left, bs)
        # all but a short final block share the same offset modulus, so
        # the whole batch is one vector draw + modulo
        full = k if self._bytes_left >= k * bs else k - 1
        offs = np.empty(k, dtype=np.uint64)
        if full:
            if self.range_len > bs:
                span = np.uint64(self.range_len - bs + 1)
                offs[:full] = np.uint64(self.start) \
                    + self.rand.next64_batch(full) % span
            else:
                # max_off == 0: the scalar path draws NOTHING here — keep
                # the shared RNG stream identical
                offs[:full] = np.uint64(self.start)
        if full < k:  # short final block, scalar (different modulus)
            self._bytes_left -= full * bs
            offs[-1], lens[-1] = self.next_block()
            return offs, lens
        self._bytes_left -= full * bs
        return offs, lens


class OffsetGenRandomAligned(OffsetGenerator):
    """Block-aligned uniform-random offsets (may repeat/miss blocks)
    (reference: OffsetGenerator.h:252-321)."""

    def __init__(self, rand: RandAlgo, num_bytes: int, block_size: int,
                 range_len: int, start: int = 0):
        if block_size <= 0:
            raise ValueError("block_size must be > 0")
        if range_len < block_size:
            raise ValueError("range smaller than block size")
        self.rand = rand
        self.num_bytes = num_bytes
        self.block_size = block_size
        self.num_blocks_in_range = range_len // block_size
        self.start = start
        self.reset()

    def reset(self) -> None:
        self._bytes_left = self.num_bytes

    def next_block(self):
        if self._bytes_left <= 0:
            return None
        length = min(self.block_size, self._bytes_left)
        blk = self.rand.next64() % self.num_blocks_in_range
        self._bytes_left -= length
        return (self.start + blk * self.block_size, length)

    def next_batch(self, max_n: int):
        if self._bytes_left <= 0:
            return None
        k, lens = self._batch_lens(max_n, self._bytes_left, self.block_size)
        blks = self.rand.next64_batch(k) % np.uint64(self.num_blocks_in_range)
        offs = np.uint64(self.start) + blks * np.uint64(self.block_size)
        self._bytes_left -= int(lens.sum())
        return offs, lens


class OffsetGenRandomAlignedFullCoverage(OffsetGenerator):
    """Aligned random permutation hitting every block exactly once.

    Uses an LCG with power-of-2 modulus m >= num_blocks; with c odd and
    a % 4 == 1 the LCG is full-period (Hull-Dobell), so iterating it visits
    every value in [0, m) exactly once; values >= num_blocks are skipped.
    This mirrors the reference's OffsetGenRandomAlignedFullCoverageV2.h:9-100
    (default generator for aligned random writes) without sharing its
    constants.
    """

    def __init__(self, rand: RandAlgo, num_bytes: int, block_size: int,
                 range_len: int, start: int = 0):
        if block_size <= 0:
            raise ValueError("block_size must be > 0")
        self.num_bytes = num_bytes
        self.block_size = block_size
        self.num_blocks = max(1, range_len // block_size)
        self.start = start
        # power-of-2 modulus >= num_blocks
        self._m = 1
        while self._m < self.num_blocks:
            self._m <<= 1
        self._mask = self._m - 1
        # full-period LCG params derived from the PRNG (Hull-Dobell for m=2^k)
        self._a = ((rand.next64() << 2) | 1) & self._mask
        if self._a % 4 != 1:
            self._a = (self._a + 2) & self._mask  # force a % 4 == 1
        if self._m >= 4 and self._a % 4 != 1:
            self._a = 5
        self._c = (rand.next64() | 1) & self._mask  # odd
        self._x0 = rand.next64() & self._mask
        self.reset()

    def reset(self) -> None:
        self._bytes_left = self.num_bytes
        self._x = self._x0
        self._emitted = 0

    def next_block(self):
        if self._bytes_left <= 0:
            return None
        # advance LCG until a value < num_blocks appears (wraps if generator
        # asked for more than one full coverage)
        while True:
            if self._emitted >= self._m:  # completed a full period: restart
                self._emitted = 0
            self._x = (self._a * self._x + self._c) & self._mask
            self._emitted += 1
            if self._x < self.num_blocks:
                break
        length = min(self.block_size, self._bytes_left)
        self._bytes_left -= length
        return (self.start + self._x * self.block_size, length)

    _JUMP = 4096  # raw LCG steps per vectorized advance

    def _ensure_jump_tables(self) -> None:
        """A[i] = a^(i+1) mod m and C[i] = c*(a^i + ... + 1) mod m, so
        x_{n+i+1} = A[i]*x_n + C[i]: one vector op yields _JUMP successive
        raw LCG states (same exactly-once sequence as next_block)."""
        if getattr(self, "_jump_a", None) is not None:
            return
        A = np.empty(self._JUMP, dtype=np.uint64)
        C = np.empty(self._JUMP, dtype=np.uint64)
        a_acc, c_acc = self._a, self._c
        for i in range(self._JUMP):
            A[i] = a_acc
            C[i] = c_acc
            a_acc = (a_acc * self._a) & self._mask
            c_acc = (c_acc * self._a + self._c) & self._mask
        self._jump_a = A
        self._jump_c = C

    def next_batch(self, max_n: int):
        if self._bytes_left <= 0:
            return None
        self._ensure_jump_tables()
        bs = self.block_size
        k_target, lens = self._batch_lens(max_n, self._bytes_left, bs)
        blks = np.empty(k_target, dtype=np.uint64)
        filled = 0
        mask = np.uint64(self._mask)
        with np.errstate(over="ignore"):
            while filled < k_target:
                # raw candidates: never cross a period boundary in one go
                take = min(self._JUMP, self._m - self._emitted)
                cand = (self._jump_a[:take] * np.uint64(self._x)
                        + self._jump_c[:take]) & mask
                good_pos = np.nonzero(cand < self.num_blocks)[0]
                need = k_target - filled
                if len(good_pos) > need:
                    # stop at the raw step of the last value we emit, so
                    # the scalar path resumes mid-stream identically
                    last_raw = int(good_pos[need - 1])
                    good_pos = good_pos[:need]
                    consumed = last_raw + 1
                else:
                    consumed = take
                n_good = len(good_pos)
                blks[filled:filled + n_good] = cand[good_pos]
                filled += n_good
                if consumed:
                    self._x = int(cand[consumed - 1])
                    self._emitted += consumed
                if self._emitted >= self._m:
                    self._emitted = 0
        offs = np.uint64(self.start) + blks * np.uint64(bs)
        self._bytes_left -= int(lens.sum())
        return offs, lens


class OffsetGenShuffleWindow(OffsetGenerator):
    """Windowed permutation over block indices (``--shufflewindow``).

    Blocks are read exactly once, grouped into consecutive windows of
    ``window_bytes``; within each window the block order is a seeded
    permutation — the access shape of a training input pipeline's
    shuffle buffer (tf.data ``shuffle(window)``, the PyTorch sampler's
    chunked shuffle): global locality stays window-bounded while the
    in-window order is random. Different seeds (epoch, rank) give
    different permutations over the same coverage, which is what the
    ``epochs`` scenario varies per epoch (docs/scenarios.md; arXiv
    2604.21275 on shuffle windows bounding pipeline throughput).
    """

    def __init__(self, num_bytes: int, block_size: int, window_bytes: int,
                 seed: int = 0, start: int = 0):
        if block_size <= 0:
            raise ValueError("block_size must be > 0")
        if window_bytes < block_size:
            raise ValueError("shuffle window smaller than block size")
        self.num_bytes = num_bytes
        self.block_size = block_size
        self.num_blocks = num_blocks_for(num_bytes, block_size)
        self.win_blocks = max(1, window_bytes // block_size)
        self.seed = seed
        self.start = start
        self.reset()

    def reset(self) -> None:
        self._window = 0
        self._i = 0
        self._perm = None

    def _window_perm(self, w: int) -> "np.ndarray":
        lo = w * self.win_blocks
        hi = min(lo + self.win_blocks, self.num_blocks)
        # Knuth-multiplicative mix so (seed, window) pairs never collide
        # into the same RandomState stream for nearby values
        rng = np.random.RandomState(
            (self.seed * 2654435761 + w * 40503) & 0x7FFFFFFF)
        return lo + rng.permutation(hi - lo)

    def next_block(self):
        if self._perm is None or self._i >= len(self._perm):
            if self._window * self.win_blocks >= self.num_blocks:
                return None
            self._perm = self._window_perm(self._window)
            self._window += 1
            self._i = 0
        blk = int(self._perm[self._i])
        self._i += 1
        off = blk * self.block_size
        return (self.start + off,
                min(self.block_size, self.num_bytes - off))

    def next_batch(self, max_n: int):
        """Closed-form batch over the current window's permutation slice
        (same sequence as the scalar path; a batch never spans windows —
        callers tolerate short batches and call again)."""
        if self._perm is None or self._i >= len(self._perm):
            if self._window * self.win_blocks >= self.num_blocks:
                return None
            self._perm = self._window_perm(self._window)
            self._window += 1
            self._i = 0
        take = self._perm[self._i:self._i + max_n]
        self._i += len(take)
        offs = take.astype(np.uint64) * np.uint64(self.block_size)
        lens = np.full(len(take), self.block_size, dtype=np.uint64)
        last_off = (self.num_blocks - 1) * self.block_size
        if self.num_bytes - last_off < self.block_size:  # short final block
            lens[offs == last_off] = self.num_bytes - last_off
        if self.start:
            offs += np.uint64(self.start)
        return offs, lens


class OffsetGenStrided(OffsetGenerator):
    """Strided access for shared files (``--strided``): worker ``rank`` starts
    at rank*block_size and advances by block_size*num_dataset_threads
    (reference: OffsetGenerator.h:323-378; SURVEY.md section 2.4)."""

    def __init__(self, num_bytes: int, block_size: int, rank: int,
                 num_dataset_threads: int, start: int = 0):
        if block_size <= 0:
            raise ValueError("block_size must be > 0")
        self.num_bytes = num_bytes
        self.block_size = block_size
        self.rank = rank
        self.stride = block_size * num_dataset_threads
        self.start = start
        self.reset()

    def reset(self) -> None:
        self._bytes_done = 0
        self._off = self.start + self.rank * self.block_size

    def next_block(self):
        if self._bytes_done >= self.num_bytes:
            return None
        length = min(self.block_size, self.num_bytes - self._bytes_done)
        off = self._off
        self._off += self.stride
        self._bytes_done += length
        return (off, length)

    def next_batch(self, max_n: int):
        if self._bytes_done >= self.num_bytes:
            return None
        offs, lens, k = self._batch_arrays(
            max_n, self.num_bytes - self._bytes_done, self.block_size,
            self._off, self.stride)
        self._off += k * self.stride
        self._bytes_done += int(lens.sum())
        return offs, lens


def num_blocks_for(num_bytes: int, block_size: int) -> int:
    return (num_bytes + block_size - 1) // block_size
