"""GCS-native object client (JSON API + OAuth2/metadata-server auth).

The idiomatic object path for TPU VMs: Google Cloud Storage via its native
JSON API — not the S3-interop XML crutch (which `s3_tk.py` also supports
against storage.googleapis.com). Selected by `gs://` bench paths.

Role parity with the reference's S3 client factory/toolkit
(`/root/reference/source/toolkits/S3Tk.cpp:167-316`), re-designed for GCS:

- auth: explicit token (--gcstoken / GOOGLE_OAUTH_ACCESS_TOKEN env) or the
  GCE/TPU-VM metadata server (workload identity), cached until expiry;
  --gcsanon for anonymous endpoints (tests, public buckets)
- single-part upload: JSON media upload
- multipart-upload analogue: parallel component objects + iterative
  `compose` (GCS's native parallel-upload idiom; 32 components per compose
  request, folded for more) behind the same
  create/upload_part/complete/abort interface the S3 worker uses
- ranged GET via `alt=media` + Range, list via `o?prefix=&pageToken=`,
  stat via object metadata GET
- tagging -> object metadata / bucket labels; versioning -> bucket
  versioning; object-lock -> bucket retentionPolicy (no per-mode concept
  in GCS: reported as GOVERNANCE when a policy exists); ACLs -> predefined
  ACLs or objectAccessControls entities

Errors raise `s3_tk.S3Error` so the object worker's error handling is
backend-agnostic.
"""

from __future__ import annotations

import http.client
import threading
import json
import os
import time
import urllib.parse
import uuid

from .s3_tk import S3Error

GCS_DEFAULT_ENDPOINT = "https://storage.googleapis.com"
METADATA_HOST_ENV = "GCE_METADATA_HOST"
METADATA_DEFAULT_HOST = "metadata.google.internal"
TOKEN_ENV = "GOOGLE_OAUTH_ACCESS_TOKEN"

#: S3 canned ACL -> GCS predefinedAcl
_CANNED_TO_PREDEFINED = {
    "private": "private",
    "public-read": "publicRead",
    "public-read-write": "publicReadWrite",
    "authenticated-read": "authenticatedRead",
    "bucket-owner-read": "bucketOwnerRead",
    "bucket-owner-full-control": "bucketOwnerFullControl",
}

#: x-amz-grant-* header -> GCS ACL role
_GRANT_HEADER_TO_ROLE = {
    "x-amz-grant-read": "READER",
    "x-amz-grant-write": "WRITER",
    "x-amz-grant-read-acp": "READER",
    "x-amz-grant-write-acp": "WRITER",
    "x-amz-grant-full-control": "OWNER",
}


class GcsTokenProvider:
    """OAuth2 access-token source with caching.

    Order: explicit token > GOOGLE_OAUTH_ACCESS_TOKEN env > GCE metadata
    server (the TPU-VM workload-identity path). Metadata tokens are cached
    and refreshed 60 s before expiry. Use `for_config` so all workers of a
    process share ONE provider (one metadata fetch per expiry, not one per
    worker — large -t runs would otherwise hammer the metadata server)."""

    _cache: "dict[tuple, GcsTokenProvider]" = {}
    _cache_lock = __import__("threading").Lock()

    def __init__(self, explicit_token: str = "", anonymous: bool = False,
                 timeout: float = 5.0):
        self.explicit_token = explicit_token
        self.anonymous = anonymous
        self.timeout = timeout
        self._lock = __import__("threading").Lock()
        self._cached = ""
        self._expires_at = 0.0

    @classmethod
    def for_config(cls, cfg) -> "GcsTokenProvider":
        key = (cfg.gcs_token, cfg.gcs_anonymous)
        with cls._cache_lock:
            provider = cls._cache.get(key)
            if provider is None:
                provider = cls(cfg.gcs_token, cfg.gcs_anonymous)
                cls._cache[key] = provider
            return provider

    def token(self) -> str:
        if self.anonymous:
            return ""
        if self.explicit_token:
            return self.explicit_token
        env_token = os.environ.get(TOKEN_ENV, "")
        if env_token:
            return env_token
        with self._lock:  # one refresh at a time across worker threads
            now = time.monotonic()
            if self._cached and now < self._expires_at - 60:
                return self._cached
            self._cached, lifetime = self._fetch_metadata_token()
            self._expires_at = now + lifetime
            return self._cached

    def _fetch_metadata_token(self) -> "tuple[str, float]":
        host = os.environ.get(METADATA_HOST_ENV, METADATA_DEFAULT_HOST)
        if ":" in host:
            hostname, port = host.rsplit(":", 1)
            conn = http.client.HTTPConnection(hostname, int(port),
                                              timeout=self.timeout)
        else:
            conn = http.client.HTTPConnection(host, timeout=self.timeout)
        try:
            conn.request(
                "GET",
                "/computeMetadata/v1/instance/service-accounts/default/token",
                headers={"Metadata-Flavor": "Google"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise S3Error(resp.status, "GcsAuthFailed",
                              f"metadata token fetch failed: "
                              f"{data.decode(errors='replace')[:200]}")
            doc = json.loads(data)
            return doc["access_token"], float(doc.get("expires_in", 300))
        except (OSError, http.client.HTTPException, ValueError,
                KeyError) as err:
            raise S3Error(
                0, "GcsAuthUnavailable",
                f"no GCS credentials: metadata server {host} unreachable "
                f"({err}); set --gcstoken, {TOKEN_ENV}, or --gcsanon"
            ) from err
        finally:
            conn.close()


class GcsClient:
    """One GCS JSON-API connection (per worker, like the reference's
    per-worker S3 client). Method surface mirrors `s3_tk.S3Client` so the
    object worker front-end is backend-agnostic."""

    _RETRY_STATUSES = (429, 500, 502, 503, 504)

    def __init__(self, endpoint: str = GCS_DEFAULT_ENDPOINT,
                 project: str = "", token_provider=None,
                 timeout: float = 60.0, num_retries: int = 0,
                 interrupt_check=None, resumable: bool = False,
                 retry_notify=None):
        parsed = urllib.parse.urlparse(
            endpoint if "//" in endpoint else "https://" + endpoint)
        self.scheme = parsed.scheme or "https"
        self.host = parsed.hostname or "storage.googleapis.com"
        self.port = parsed.port or (443 if self.scheme == "https" else 80)
        self.project = project
        self.auth = token_provider or GcsTokenProvider(anonymous=True)
        self.timeout = timeout
        self.num_retries = num_retries
        self.interrupt_check = interrupt_check
        # retry_notify(slept_secs): feeds the worker's IoRetries audit
        self.retry_notify = retry_notify
        #: --gcsresumable: serve the MPU interface via resumable upload
        #: sessions (the native GCS large-single-object idiom) instead of
        #: component objects + compose
        self.resumable = resumable
        self._sessions: "dict[str, dict]" = {}
        # per-thread connections, same discipline as S3Client: a shared
        # singleton client (--s3single) stays safe because every worker
        # thread drives its own connection
        self._conn_local = threading.local()
        self._all_conns: "list[http.client.HTTPConnection]" = []
        self._conns_lock = threading.Lock()

    # -- plumbing ------------------------------------------------------------

    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._conn_local, "conn", None)
        if conn is None:
            cls = (http.client.HTTPSConnection if self.scheme == "https"
                   else http.client.HTTPConnection)
            conn = cls(self.host, self.port, timeout=self.timeout)
            self._conn_local.conn = conn
            with self._conns_lock:
                self._all_conns.append(conn)
        return conn

    def _drop_connection(self) -> None:
        conn = getattr(self._conn_local, "conn", None)
        if conn is not None:
            conn.close()
            self._conn_local.conn = None
            with self._conns_lock:
                try:
                    self._all_conns.remove(conn)
                except ValueError:
                    pass

    def close(self) -> None:
        with self._conns_lock:
            conns, self._all_conns = self._all_conns, []
        for conn in conns:
            conn.close()
        self._conn_local = threading.local()

    @staticmethod
    def _obj_path(bucket: str, key: str) -> str:
        return (f"/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"
                f"/o/{urllib.parse.quote(key, safe='')}")

    @staticmethod
    def _bucket_path(bucket: str) -> str:
        return f"/storage/v1/b/{urllib.parse.quote(bucket, safe='')}"

    def request(self, method: str, path: str,
                query: "dict | None" = None, body: bytes = b"",
                headers: "dict | None" = None,
                want_body: bool = True) -> "tuple[int, dict, bytes]":
        """One JSON-API request with transient-error retries and
        interruption checks between attempts (same contract as
        S3Client.request)."""
        last_err = None
        for attempt in range(self.num_retries + 1):
            if self.interrupt_check:
                self.interrupt_check()
            try:
                status, resp_headers, data = self._request_once(
                    method, path, query, body, headers, want_body)
            except (OSError, http.client.HTTPException) as err:
                last_err = err
                if attempt < self.num_retries:
                    from .s3_tk import retry_backoff_sleep
                    retry_backoff_sleep(attempt, self.retry_notify)
                continue
            if status in self._RETRY_STATUSES and attempt < self.num_retries:
                from .s3_tk import retry_backoff_sleep
                retry_backoff_sleep(attempt, self.retry_notify)
                continue
            return status, resp_headers, data
        raise last_err if last_err is not None else S3Error(
            503, "RetryExhausted", "request retries exhausted")

    def _request_once(self, method, path, query, body, headers,
                      want_body) -> "tuple[int, dict, bytes]":
        url = path
        if query:
            url += "?" + urllib.parse.urlencode(
                {k: str(v) for k, v in query.items()})
        hdrs = dict(headers or {})
        hdrs["Host"] = self.host if self.port in (80, 443) \
            else f"{self.host}:{self.port}"
        token = self.auth.token()
        if token:
            hdrs["Authorization"] = f"Bearer {token}"
        conn = self._connection()
        try:
            conn.request(method, url, body=body or None, headers=hdrs)
            resp = conn.getresponse()
            data = resp.read() if want_body or resp.status >= 300 else b""
            if not want_body and resp.status < 300:
                resp.read()  # drain for keep-alive
            return resp.status, dict(resp.getheaders()), data
        except (http.client.HTTPException, OSError):
            self._drop_connection()  # broken keep-alive: this thread's
            raise

    @staticmethod
    def _check(status: int, data: bytes, ok=(200, 204)) -> None:
        if status in ok:
            return
        code, message = "GcsError", data.decode(errors="replace")[:300]
        try:
            doc = json.loads(data)
            err = doc.get("error", {})
            code = str(err.get("code", code))
            message = err.get("message", message)
        except (ValueError, AttributeError):
            pass
        raise S3Error(status, code, message)

    # -- bucket ops ----------------------------------------------------------

    def create_bucket(self, bucket: str) -> None:
        body = json.dumps({"name": bucket}).encode()
        query = {"project": self.project} if self.project else {}
        status, _, data = self.request(
            "POST", "/storage/v1/b", query=query, body=body,
            headers={"Content-Type": "application/json"})
        if status == 409:  # already exists/owned: treat as success
            return
        self._check(status, data, ok=(200,))

    def delete_bucket(self, bucket: str) -> None:
        status, _, data = self.request("DELETE", self._bucket_path(bucket))
        self._check(status, data)

    def head_bucket(self, bucket: str) -> bool:
        status, _, _ = self.request("GET", self._bucket_path(bucket))
        return status == 200

    # -- object data ops -----------------------------------------------------

    def put_object(self, bucket: str, key: str, body: bytes,
                   extra_headers: "dict | None" = None) -> None:
        status, _, data = self.request(
            "POST",
            f"/upload/storage/v1/b/{urllib.parse.quote(bucket, safe='')}/o",
            query={"uploadType": "media", "name": key}, body=body,
            headers=extra_headers)
        self._check(status, data, ok=(200,))

    def get_object(self, bucket: str, key: str,
                   range_start: "int | None" = None,
                   range_len: "int | None" = None,
                   extra_headers: "dict | None" = None) -> bytes:
        headers = dict(extra_headers or {})
        if range_start is not None:
            end = "" if range_len is None else str(range_start + range_len - 1)
            headers["Range"] = f"bytes={range_start}-{end}"
        status, _, data = self.request(
            "GET", self._obj_path(bucket, key), query={"alt": "media"},
            headers=headers)
        if status not in (200, 206):
            self._check(status, data, ok=())
        return data

    def get_object_discard(self, bucket: str, key: str,
                           range_start: "int | None" = None,
                           range_len: "int | None" = None,
                           extra_headers: "dict | None" = None) -> int:
        """Chunked streaming download, body dropped (--s3fastget
        equivalent); returns the byte count."""
        from .s3_tk import run_discard_with_retries
        return run_discard_with_retries(
            lambda: self._get_discard_once(bucket, key, range_start,
                                           range_len, extra_headers),
            self.num_retries, self._RETRY_STATUSES, self.interrupt_check,
            retry_notify=self.retry_notify)

    def _get_discard_once(self, bucket, key, range_start, range_len,
                          extra_headers) -> "tuple[int, int]":
        headers = dict(extra_headers or {})
        if range_start is not None:
            end = "" if range_len is None else str(range_start + range_len - 1)
            headers["Range"] = f"bytes={range_start}-{end}"
        headers["Host"] = self.host if self.port in (80, 443) \
            else f"{self.host}:{self.port}"
        token = self.auth.token()
        if token:
            headers["Authorization"] = f"Bearer {token}"
        conn = self._connection()
        try:
            conn.request("GET", self._obj_path(bucket, key) + "?alt=media",
                         headers=headers)
            resp = conn.getresponse()
            if resp.status in self._RETRY_STATUSES:
                resp.read()  # drain for keep-alive
                return resp.status, 0
            if resp.status not in (200, 206):
                self._check(resp.status, resp.read(), ok=())
            total = 0
            chunks = 0
            while True:
                chunk = resp.read(1 << 20)
                if not chunk:
                    break
                total += len(chunk)
                chunks += 1
                if self.interrupt_check and chunks % 16 == 0:
                    self.interrupt_check()
            return resp.status, total
        except (http.client.HTTPException, OSError):
            self._drop_connection()
            raise

    def head_object(self, bucket: str, key: str,
                    extra_headers: "dict | None" = None) -> "dict[str, str]":
        status, _, data = self.request("GET", self._obj_path(bucket, key),
                                       headers=extra_headers)
        if status != 200:
            raise S3Error(status, "NotFound", key)
        meta = json.loads(data)
        # header-shaped view so stat phases are backend-agnostic
        out = {str(k): str(v) for k, v in meta.items()
               if not isinstance(v, (dict, list))}
        out["content-length"] = str(meta.get("size", ""))
        out["etag"] = str(meta.get("etag", meta.get("md5Hash", "")))
        return out

    def delete_object(self, bucket: str, key: str) -> None:
        status, _, data = self.request("DELETE", self._obj_path(bucket, key))
        self._check(status, data)

    def delete_objects(self, bucket: str, keys: "list[str]") -> None:
        """GCS has no single-request multi-delete in the JSON API (batch
        endpoints are multipart/mixed); loop with the usual interrupt
        checks — the phase accounting stays identical."""
        failures = []
        for key in keys:
            try:
                self.delete_object(bucket, key)
            except S3Error as err:
                failures.append((key, err.code))
        if failures:
            key, code = failures[0]
            raise S3Error(200, code or "MultiDeleteError",
                          f"{len(failures)} object(s) failed to delete, "
                          f"first: {key}")

    def list_objects(self, bucket: str, prefix: str = "",
                     max_keys: int = 1000,
                     continuation_token: str = ""
                     ) -> "tuple[list[str], str]":
        entries, next_token = self.list_objects_entries(
            bucket, prefix, max_keys, continuation_token)
        return [k for k, _size in entries], next_token

    def list_objects_entries(self, bucket: str, prefix: str = "",
                             max_keys: int = 1000,
                             continuation_token: str = ""
                             ) -> "tuple[list[tuple[str, int]], str]":
        """Sized listing page for the bucket treescan (same surface as
        S3Client.list_objects_entries)."""
        query = {"maxResults": str(max_keys)}
        if prefix:
            query["prefix"] = prefix
        if continuation_token:
            query["pageToken"] = continuation_token
        status, _, data = self.request(
            "GET", self._bucket_path(bucket) + "/o", query=query)
        self._check(status, data, ok=(200,))
        doc = json.loads(data)
        entries = [(item["name"], int(item.get("size", 0)))
                   for item in doc.get("items", [])]
        return entries, doc.get("nextPageToken", "")

    # -- multipart analogue: component objects + compose ---------------------

    #: GCS compose accepts at most 32 source objects per request
    _COMPOSE_BATCH = 32

    def _part_key(self, key: str, upload_id: str, part_number: int) -> str:
        return f"{key}.{upload_id}.p{part_number:06d}"

    def create_multipart_upload(self, bucket: str, key: str,
                                extra_headers: "dict | None" = None) -> str:
        """Compose mode (default): no server-side session — the upload id
        namespaces the component objects of GCS's native parallel-upload
        idiom. Resumable mode (--gcsresumable): initiates a resumable
        upload session (uploadType=resumable; the Location header carries
        the session URI) and the id keys the local session state."""
        if self.resumable:
            return self._resumable_create(bucket, key, extra_headers)
        del bucket, key, extra_headers  # no server round trip needed
        return "cmp" + uuid.uuid4().hex[:16]

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_number: int, body: bytes,
                    extra_headers: "dict | None" = None) -> str:
        if upload_id in self._sessions:
            return self._resumable_put_chunk(upload_id, part_number, body)
        part_key = self._part_key(key, upload_id, part_number)
        self.put_object(bucket, part_key, body, extra_headers=extra_headers)
        return part_key  # the "etag" slot carries the component name

    # -- resumable upload sessions (--gcsresumable) --------------------------
    # Protocol: initiate (POST uploadType=resumable -> session URI), then
    # sequential chunk PUTs with "Content-Range: bytes S-E/*" answered by
    # 308 Resume Incomplete + a Range header acknowledging the committed
    # prefix, finalize with an empty "bytes */TOTAL" PUT, cancel with
    # DELETE on the session URI (status 499). The native GCS idiom for
    # large single-stream objects; the reference's closest analogue is the
    # sequential MPU path (LocalWorker.cpp:4905+).

    @staticmethod
    def _upload_obj_path(bucket: str) -> str:
        return f"/upload/storage/v1/b/{urllib.parse.quote(bucket, safe='')}/o"

    def _resumable_create(self, bucket: str, key: str,
                          extra_headers: "dict | None") -> str:
        status, headers, data = self.request(
            "POST", self._upload_obj_path(bucket),
            query={"uploadType": "resumable", "name": key},
            body=json.dumps({"name": key}).encode(),
            headers={"Content-Type": "application/json; charset=UTF-8",
                     **(extra_headers or {})})
        self._check(status, data, ok=(200,))
        location = next((v for k, v in headers.items()
                         if k.lower() == "location"), "")
        if not location:
            raise S3Error(500, "NoSessionUri",
                          "resumable initiation returned no Location")
        parsed = urllib.parse.urlparse(location)
        upload_id = "rs" + uuid.uuid4().hex[:16]
        self._sessions[upload_id] = {
            "path": parsed.path,
            "query": dict(urllib.parse.parse_qsl(parsed.query)),
            "offset": 0,
            "next_part": 1,
        }
        return upload_id

    @staticmethod
    def _committed_end(headers: dict) -> int:
        """Bytes committed server-side, from the 308 Range header
        ("Range: bytes=0-N" -> N+1); no header means nothing stored."""
        rng = next((v for k, v in headers.items()
                    if k.lower() == "range"), "")
        if not rng.startswith("bytes=0-"):
            return 0
        try:
            return int(rng[len("bytes=0-"):]) + 1
        except ValueError:
            return 0

    def _resumable_put_chunk(self, upload_id: str, part_number: int,
                             body: bytes) -> str:
        sess = self._sessions[upload_id]
        if part_number != sess["next_part"]:
            raise S3Error(
                400, "OutOfOrderChunk",
                f"resumable uploads are sequential per worker: got part "
                f"{part_number}, expected {sess['next_part']} "
                f"(--gcsresumable cannot serve shared cross-worker MPUs)")
        data = bytes(body)
        first_byte = sess["offset"]
        # GCS may answer 308 without having persisted anything after a
        # transient backend error; the protocol expects the client to
        # resend the same chunk, so a zero-progress 308 only becomes
        # fatal after the retry budget is spent
        no_progress_left = self.num_retries + 1
        while data:
            start = sess["offset"]
            end = start + len(data) - 1
            status, headers, resp = self.request(
                "PUT", sess["path"], query=sess["query"], body=data,
                headers={"Content-Range": f"bytes {start}-{end}/*"})
            if status not in (308, 200, 201):
                self._check(status, resp, ok=(308, 200, 201))
            if status in (200, 201):  # server finalized early
                sess["offset"] = end + 1
                break
            committed = self._committed_end(headers)
            if committed <= start:
                no_progress_left -= 1
                if no_progress_left <= 0:
                    raise S3Error(
                        500, "NoChunkProgress",
                        f"308 acknowledged {committed} bytes, already had "
                        f"{start} committed, and {self.num_retries + 1} "
                        f"attempts (initial send + {self.num_retries} "
                        f"resends) made no progress — resumable session "
                        f"stalled")
                # a zero-progress 308 means the backend is struggling:
                # back off like the request-level retry path instead of
                # hammering it with back-to-back resends
                resend_num = self.num_retries + 1 - no_progress_left
                time.sleep(0.2 * resend_num)
                continue  # resend the same chunk
            no_progress_left = self.num_retries + 1
            # partial accept: resend the unacknowledged tail (this is the
            # 308-driven resume loop of the protocol)
            data = data[committed - start:]
            sess["offset"] = committed
        sess["next_part"] += 1
        return f"bytes-{first_byte}-{sess['offset'] - 1}"

    def _compose(self, bucket: str, sources: "list[str]",
                 dest: str) -> None:
        body = json.dumps({
            "sourceObjects": [{"name": s} for s in sources],
            "destination": {"contentType": "application/octet-stream"},
        }).encode()
        status, _, data = self.request(
            "POST", self._obj_path(bucket, dest) + "/compose", body=body,
            headers={"Content-Type": "application/json"})
        self._check(status, data, ok=(200,))

    def complete_multipart_upload(self, bucket: str, key: str,
                                  upload_id: str, parts,
                                  checksum_algo: str = "") -> None:
        """Compose mode: fold the ordered components into the destination
        (up to 32 per compose request, intermediates re-composed
        iteratively, then all temporaries deleted). Resumable mode: an
        empty finalize PUT declaring the total ("bytes */TOTAL")."""
        del checksum_algo  # GCS validates via per-object crc32c instead
        sess = self._sessions.pop(upload_id, None)
        if sess is not None:
            total = sess["offset"]
            status, _, data = self.request(
                "PUT", sess["path"], query=sess["query"], body=b"",
                headers={"Content-Range": f"bytes */{total}"})
            self._check(status, data, ok=(200, 201))
            return None
        sources = [self._part_key(key, upload_id, p[0])
                   for p in sorted(parts)]
        temps = list(sources)
        level = 0
        while len(sources) > self._COMPOSE_BATCH:
            next_level = []
            for i in range(0, len(sources), self._COMPOSE_BATCH):
                batch = sources[i:i + self._COMPOSE_BATCH]
                if len(batch) == 1:
                    next_level.append(batch[0])
                    continue
                inter = f"{key}.{upload_id}.c{level}.{i:06d}"
                self._compose(bucket, batch, inter)
                next_level.append(inter)
                temps.append(inter)
            sources = next_level
            level += 1
        self._compose(bucket, sources, key)
        for temp in temps:
            try:
                self.delete_object(bucket, temp)
            except S3Error:
                pass  # best-effort cleanup, like MPU abort
        return None

    def abort_multipart_upload(self, bucket: str, key: str,
                               upload_id: str) -> None:
        sess = self._sessions.pop(upload_id, None)
        if sess is not None:
            # cancel the session: DELETE on the session URI; GCS answers
            # 499 Client Closed Request for a cancelled session
            status, _, data = self.request(
                "DELETE", sess["path"], query=sess["query"])
            if status not in (200, 204, 499):
                self._check(status, data, ok=(200, 204, 499))
            return
        prefix = f"{key}.{upload_id}."
        token = ""
        while True:
            keys, token = self.list_objects(bucket, prefix=prefix,
                                            continuation_token=token)
            for k in keys:
                try:
                    self.delete_object(bucket, k)
                except S3Error:
                    pass
            if not token:
                return

    def list_multipart_uploads(self, bucket: str, prefix: str = "",
                               key_marker: str = "",
                               upload_id_marker: str = ""
                               ) -> "tuple[list[tuple[str, str]], str, str]":
        """Leftover component objects, grouped by (key, upload id) — the
        cleanup-tool contract of the S3 version."""
        del upload_id_marker
        uploads = set()
        token = key_marker
        while True:
            keys, token = self.list_objects(bucket, prefix=prefix,
                                            continuation_token=token)
            for k in keys:
                base, _, tail = k.rpartition(".p")
                if not tail.isdigit():
                    continue
                obj_key, _, upload_id = base.rpartition(".")
                if upload_id.startswith("cmp"):
                    uploads.add((obj_key, upload_id))
            if not token:
                return sorted(uploads), "", ""

    # -- metadata ops (tagging / ACL / versioning / retention) ---------------

    def _patch_object(self, bucket: str, key: str, doc: dict,
                      query: "dict | None" = None) -> bytes:
        status, _, data = self.request(
            "PATCH", self._obj_path(bucket, key), query=query,
            body=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        self._check(status, data, ok=(200,))
        return data

    def _patch_bucket(self, bucket: str, doc: dict,
                      query: "dict | None" = None) -> bytes:
        status, _, data = self.request(
            "PATCH", self._bucket_path(bucket), query=query,
            body=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        self._check(status, data, ok=(200,))
        return data

    def put_object_tagging(self, bucket: str, key: str,
                           tags: "dict[str, str]") -> None:
        self._patch_object(bucket, key, {"metadata": tags})

    def get_object_tagging(self, bucket: str, key: str) -> "dict[str, str]":
        status, _, data = self.request("GET", self._obj_path(bucket, key))
        self._check(status, data, ok=(200,))
        return json.loads(data).get("metadata", {}) or {}

    def delete_object_tagging(self, bucket: str, key: str) -> None:
        self._patch_object(bucket, key, {"metadata": None})

    def put_bucket_tagging(self, bucket: str,
                           tags: "dict[str, str]") -> None:
        self._patch_bucket(bucket, {"labels": tags})

    def get_bucket_tagging(self, bucket: str) -> "dict[str, str]":
        status, _, data = self.request("GET", self._bucket_path(bucket))
        self._check(status, data, ok=(200,))
        return json.loads(data).get("labels", {}) or {}

    def delete_bucket_tagging(self, bucket: str) -> None:
        self._patch_bucket(bucket, {"labels": None})

    def put_bucket_versioning(self, bucket: str, enabled: bool) -> None:
        self._patch_bucket(bucket, {"versioning": {"enabled": enabled}})

    def get_bucket_versioning(self, bucket: str) -> str:
        status, _, data = self.request("GET", self._bucket_path(bucket))
        self._check(status, data, ok=(200,))
        enabled = json.loads(data).get("versioning", {}).get("enabled")
        return "Enabled" if enabled else ("Suspended" if enabled is False
                                          else "")

    def put_object_lock_configuration(self, bucket: str,
                                      mode: str = "GOVERNANCE",
                                      days: int = 1) -> None:
        """GCS analogue: bucket retention policy (no GOVERNANCE/COMPLIANCE
        mode concept — empty mode clears the policy)."""
        policy = {"retentionPeriod": str(days * 86400)} if mode else None
        self._patch_bucket(bucket, {"retentionPolicy": policy})

    def get_object_lock_configuration(self, bucket: str) -> str:
        status, _, data = self.request("GET", self._bucket_path(bucket))
        self._check(status, data, ok=(200,))
        policy = json.loads(data).get("retentionPolicy")
        # reported as GOVERNANCE when a policy exists (documented mapping)
        return "GOVERNANCE" if policy else ""

    @staticmethod
    def _acl_entries(acl: str, acl_headers: "dict | None") -> "tuple":
        """(predefinedAcl, entity-entries) from a canned ACL name or the
        worker's x-amz-grant-* header dict."""
        if acl:
            return _CANNED_TO_PREDEFINED.get(acl, ""), []
        entries = []
        for header, value in (acl_headers or {}).items():
            role = _GRANT_HEADER_TO_ROLE.get(header.lower())
            if header.lower() == "x-amz-acl":
                return _CANNED_TO_PREDEFINED.get(value, ""), []
            if not role:
                continue
            for grant in value.split(","):
                gtype, _, name = grant.strip().partition("=")
                name = name.strip('"')
                if gtype in ("id", "emailAddress"):
                    entity = f"user-{name}"
                elif gtype == "uri":
                    entity = ("allUsers" if name.endswith("AllUsers")
                              else "allAuthenticatedUsers"
                              if name.endswith("AuthenticatedUsers")
                              else f"group-{name}")
                else:
                    entity = grant.strip()
                entries.append({"entity": entity, "role": role})
        return "", entries

    def put_object_acl(self, bucket: str, key: str, acl: str = "",
                       acl_headers: "dict | None" = None) -> None:
        predefined, entries = self._acl_entries(acl, acl_headers)
        if predefined:
            self._patch_object(bucket, key, {},
                               query={"predefinedAcl": predefined})
        else:
            self._patch_object(bucket, key, {"acl": entries})

    def get_object_acl(self, bucket: str, key: str) -> bytes:
        status, _, data = self.request(
            "GET", self._obj_path(bucket, key) + "/acl")
        self._check(status, data, ok=(200,))
        return data

    def put_bucket_acl(self, bucket: str, acl: str = "",
                       acl_headers: "dict | None" = None) -> None:
        predefined, entries = self._acl_entries(acl, acl_headers)
        if predefined:
            self._patch_bucket(bucket, {},
                               query={"predefinedAcl": predefined})
        else:
            self._patch_bucket(bucket, {"acl": entries})

    def get_bucket_acl(self, bucket: str) -> bytes:
        status, _, data = self.request(
            "GET", self._bucket_path(bucket) + "/acl")
        self._check(status, data, ok=(200,))
        return data
