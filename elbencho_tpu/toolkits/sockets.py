"""TCP socket toolkit for the netbench data plane.

Reference: source/toolkits/net/BasicSocket.{h,cpp} (791 LoC) + Socket base —
connect/bind/listen/accept, timed recv (recvT/recvExactT), poll-based
waiting, SO_RCVBUF/SNDBUF sizing, SO_BINDTODEVICE, TCP_NODELAY, keepalive
(BasicSocket.h:17-110).
"""

from __future__ import annotations

import socket
import time


class SocketError(OSError):
    pass


class BasicSocket:
    """Thin wrapper with the reference's semantics: explicit timeouts,
    exact-length receive, optional device binding and buffer sizing."""

    def __init__(self, sock: "socket.socket | None" = None):
        self.sock = sock or socket.socket(socket.AF_INET, socket.SOCK_STREAM)

    # -- setup ---------------------------------------------------------------

    def set_no_delay(self, enabled: bool = True) -> None:
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                             1 if enabled else 0)

    def set_keepalive(self, enabled: bool = True) -> None:
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE,
                             1 if enabled else 0)

    def set_buffer_sizes(self, recv_size: int = 0, send_size: int = 0) -> None:
        if recv_size:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF,
                                 recv_size)
        if send_size:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF,
                                 send_size)

    def bind_to_device(self, netdev: str) -> None:
        """--netdevs client binding (reference: SO_BINDTODEVICE,
        LocalWorker.cpp:762-766)."""
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_BINDTODEVICE,
                             netdev.encode() + b"\0")

    # -- server --------------------------------------------------------------

    def listen(self, host: str, port: int, backlog: int = 128) -> None:
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(backlog)

    def accept(self, timeout: "float | None" = None) -> "BasicSocket":
        self.sock.settimeout(timeout)
        conn, _addr = self.sock.accept()
        wrapped = BasicSocket(conn)
        wrapped.set_no_delay()
        return wrapped

    # -- client --------------------------------------------------------------

    def connect_with_retry(self, host: str, port: int,
                           retry_secs: float = 20.0,
                           interrupt_check=None, setup_fn=None) -> None:
        """Connect, retrying until the server side is up (reference:
        netbench client connect retry 20s, LocalWorker.cpp:784-818).
        ``setup_fn(sock)`` re-applies socket options (buffer sizes, device
        binding) to each fresh socket created for a retry."""
        deadline = time.monotonic() + retry_secs
        while True:
            if interrupt_check:
                interrupt_check()
            try:
                self.sock.settimeout(3.0)
                self.sock.connect((host, port))
                self.set_no_delay()
                return
            except OSError as err:
                try:
                    self.sock.close()
                except OSError:
                    pass
                if time.monotonic() >= deadline:
                    raise SocketError(
                        f"connect to {host}:{port} failed: {err}") from err
                self.sock = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
                if setup_fn:
                    setup_fn(self)
                time.sleep(0.5)

    # -- I/O -----------------------------------------------------------------

    def send_all(self, data: "bytes | memoryview",
                 timeout: "float | None" = None) -> None:
        self.sock.settimeout(timeout)
        self.sock.sendall(data)

    def recv_exact(self, num_bytes: int, timeout: "float | None" = None,
                   interrupt_check=None) -> bytes:
        """Receive exactly num_bytes or raise SocketError after ``timeout``
        seconds of overall inactivity (reference: recvExactT). Short recv
        slices let interrupt checks run on idle connections."""
        chunks = []
        remaining = num_bytes
        deadline = time.monotonic() + (timeout or 5.0)
        self.sock.settimeout(1.0)
        while remaining:
            try:
                chunk = self.sock.recv(min(remaining, 1 << 20))
            except socket.timeout:
                if interrupt_check:
                    interrupt_check()
                if time.monotonic() >= deadline:
                    raise SocketError(
                        f"recv timed out after {timeout}s "
                        f"({num_bytes - remaining}/{num_bytes} bytes)")
                continue
            if not chunk:
                raise SocketError("connection closed by peer")
            chunks.append(chunk)
            remaining -= len(chunk)
            deadline = time.monotonic() + (timeout or 5.0)  # progress resets
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
