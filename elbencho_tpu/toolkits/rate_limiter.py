"""Rate limiting toolkits.

Reference: source/toolkits/RateLimiter.h (per-thread bytes/sec with
sleep-to-second-boundary) and RateLimiterRWMixThreads.{h,cpp} (process-wide
read/write byte-ratio balancer for --rwmixthrpct with headroom + condvars).
"""

from __future__ import annotations

import threading
import time


class RateLimiter:
    """Per-thread bytes-per-second limiter (reference: RateLimiter.h:1-72).

    Tokens refill once per wall-clock second; wait() blocks until the block's
    bytes fit in the current second's budget.
    """

    def __init__(self, bytes_per_sec: int):
        self.bytes_per_sec = bytes_per_sec
        self._window_start = time.monotonic()
        self._bytes_in_window = 0

    def wait(self, num_bytes: int) -> None:
        if self.bytes_per_sec <= 0:
            return
        now = time.monotonic()
        elapsed = now - self._window_start
        if elapsed >= 1.0:
            self._window_start = now
            self._bytes_in_window = 0
        elif self._bytes_in_window + num_bytes > self.bytes_per_sec:
            # sleep to the next second boundary, then open a fresh window
            time.sleep(max(0.0, 1.0 - elapsed))
            self._window_start = time.monotonic()
            self._bytes_in_window = 0
        self._bytes_in_window += num_bytes


class RateLimiterRWMixThreads:
    """Keeps the read:write *byte ratio* of a mixed-threads phase near the
    requested percentage (``--rwmixthrpct``).

    Process-wide shared counters (the reference uses static atomics +
    condvars, RateLimiterRWMixThreads.h:22-200): readers wait while reads are
    ahead of the target ratio beyond a headroom allowance, writers wait in
    the symmetric case. Waiters are woken whenever the other side makes
    progress.
    """

    _HEADROOM_BYTES = 16 * 1024 * 1024

    def __init__(self, read_pct: int):
        if not 0 < read_pct < 100:
            raise ValueError("read percentage must be in (0, 100)")
        self.read_pct = read_pct
        self._lock = threading.Condition()
        self._read_bytes = 0
        self._write_bytes = 0
        self._interrupted = False

    def reset(self) -> None:
        with self._lock:
            self._read_bytes = 0
            self._write_bytes = 0
            self._interrupted = False

    def interrupt(self) -> None:
        with self._lock:
            self._interrupted = True
            self._lock.notify_all()

    def _read_target(self) -> int:
        total = self._read_bytes + self._write_bytes
        return int(total * self.read_pct / 100)

    def wait_read(self, num_bytes: int, timeout: float = 0.5) -> None:
        with self._lock:
            while (not self._interrupted
                   and self._read_bytes > self._read_target() + self._HEADROOM_BYTES):
                if not self._lock.wait(timeout):
                    break
            self._read_bytes += num_bytes
            self._lock.notify_all()

    def wait_write(self, num_bytes: int, timeout: float = 0.5) -> None:
        with self._lock:
            while (not self._interrupted
                   and self._write_bytes > (self._read_bytes + self._write_bytes
                                            - self._read_target()) + self._HEADROOM_BYTES):
                if not self._lock.wait(timeout):
                    break
            self._write_bytes += num_bytes
            self._lock.notify_all()
