"""Rate limiting toolkits.

Reference: source/toolkits/RateLimiter.h (per-thread bytes/sec with
sleep-to-second-boundary) and RateLimiterRWMixThreads.{h,cpp} (process-wide
read/write byte-ratio balancer for --rwmixthrpct with headroom + condvars).
"""

from __future__ import annotations

import threading
import time


class RateLimiter:
    """Per-thread bytes-per-second limiter (reference: RateLimiter.h:1-72).

    Tokens refill once per wall-clock second; wait() blocks until the block's
    bytes fit in the current second's budget.
    """

    def __init__(self, bytes_per_sec: int):
        self.bytes_per_sec = bytes_per_sec
        self._window_start = time.monotonic()
        self._bytes_in_window = 0

    def wait(self, num_bytes: int) -> None:
        if self.bytes_per_sec <= 0:
            return
        now = time.monotonic()
        elapsed = now - self._window_start
        if elapsed >= 1.0:
            self._window_start = now
            self._bytes_in_window = 0
        elif self._bytes_in_window + num_bytes > self.bytes_per_sec:
            # sleep to the next second boundary, then open a fresh window
            time.sleep(max(0.0, 1.0 - elapsed))
            self._window_start = time.monotonic()
            self._bytes_in_window = 0
        self._bytes_in_window += num_bytes


class DataLoaderPacer:
    """Training input-pipeline consumer emulation (``--scenario
    dataloader``; arXiv 2604.21275).

    The worker's read loop calls :meth:`on_block` per completed block;
    every ``batch_blocks`` blocks close a batch. A closed batch pays a
    CPU decode burn (busy-spin for ``decode_usec`` — a sleep would
    release the core a real decoder occupies), then waits for the
    consume clock: one batch is consumed every ``step_usec`` from the
    first block, and the reader may run at most ``prefetch`` batches
    ahead of it. Storage faster than the cadence fills the prefetch
    queue and idles (the healthy-pipeline shape); storage slower than
    the cadence never waits here — its rate IS the (degraded) pipeline
    rate the cadence verdict names.
    """

    def __init__(self, batch_blocks: int, step_usec: int,
                 decode_usec: int, prefetch: int,
                 interrupt_check=None):
        self.batch_blocks = max(batch_blocks, 1)
        self.step_secs = max(step_usec, 0) / 1e6
        self.decode_secs = max(decode_usec, 0) / 1e6
        self.prefetch = max(prefetch, 1)
        self._interrupt_check = interrupt_check
        self._blocks = 0
        self.batches = 0
        self._t0 = 0.0
        self.wait_secs = 0.0   # consume-clock idle (prefetch full)
        self.decode_secs_total = 0.0

    def on_block(self) -> None:
        if not self._t0:
            self._t0 = time.monotonic()
        self._blocks += 1
        if self._blocks % self.batch_blocks:
            return
        self.batches += 1
        if self.decode_secs:
            end = time.perf_counter() + self.decode_secs
            while time.perf_counter() < end:
                pass
            self.decode_secs_total += self.decode_secs
        if not self.step_secs:
            return
        # batch b may complete no earlier than (b - prefetch) steps
        # after the first block: that is when the consumer frees the
        # prefetch slot this batch lands in
        target = self._t0 + (self.batches - self.prefetch) * self.step_secs
        while True:
            now = time.monotonic()
            if now >= target:
                return
            if self._interrupt_check is not None:
                self._interrupt_check()
            self.wait_secs += min(target - now, 0.05)
            time.sleep(min(target - now, 0.05))


class RateLimiterRWMixThreads:
    """Keeps the read:write *byte ratio* of a mixed-threads phase near the
    requested percentage (``--rwmixthrpct``).

    Process-wide shared counters (the reference uses static atomics +
    condvars, RateLimiterRWMixThreads.h:22-200): readers wait while reads are
    ahead of the target ratio beyond a headroom allowance, writers wait in
    the symmetric case. Waiters are woken whenever the other side makes
    progress.
    """

    _HEADROOM_BYTES = 16 * 1024 * 1024

    def __init__(self, read_pct: int):
        if not 0 < read_pct < 100:
            raise ValueError("read percentage must be in (0, 100)")
        self.read_pct = read_pct
        self._lock = threading.Condition()
        self._read_bytes = 0
        self._write_bytes = 0
        self._interrupted = False

    def reset(self) -> None:
        with self._lock:
            self._read_bytes = 0
            self._write_bytes = 0
            self._interrupted = False

    def interrupt(self) -> None:
        with self._lock:
            self._interrupted = True
            self._lock.notify_all()

    def _read_target(self) -> int:
        total = self._read_bytes + self._write_bytes
        return int(total * self.read_pct / 100)

    def wait_read(self, num_bytes: int, timeout: float = 0.5) -> None:
        with self._lock:
            while (not self._interrupted
                   and self._read_bytes > self._read_target() + self._HEADROOM_BYTES):
                if not self._lock.wait(timeout):
                    break
            self._read_bytes += num_bytes
            self._lock.notify_all()

    def wait_write(self, num_bytes: int, timeout: float = 0.5) -> None:
        with self._lock:
            while (not self._interrupted
                   and self._write_bytes > (self._read_bytes + self._write_bytes
                                            - self._read_target()) + self._HEADROOM_BYTES):
                if not self._lock.wait(timeout):
                    break
            self._write_bytes += num_bytes
            self._lock.notify_all()
